"""``accelerate-tpu lint`` — the TPU correctness linter CLI.

AST tier over source paths plus the ``--selfcheck`` seeded-defect run
(which also exercises the jaxpr tier against a CPU fake mesh, so CI can
prove the detectors fire without touching hardware). Exit code is the CI
contract from ``analysis.report.exit_code``: nonzero on any
error-severity finding (or any finding at all under ``--strict``).

Examples::

    accelerate-tpu lint accelerate_tpu/            # lint the tree
    accelerate-tpu lint --selfcheck                # prove the rules fire
    accelerate-tpu lint src/train.py --format json # machine-readable
    accelerate-tpu lint pkg/ --format sarif        # CI PR annotation
    accelerate-tpu lint pkg/ --select TPU201,TPU202

A ``.tpulint.toml`` found by walking up from the working directory
supplies the default ``--format``, globally disabled rules, and per-path
suppressions (``analysis.project_config``); CLI flags win.

The jaxpr tier for *your* step function is programmatic —
``Accelerator.lint(step_fn, *sample_args)`` or
``accelerate_tpu.analysis.lint_step`` — because it needs sample shapes
and your mesh, which a file path cannot carry.
"""

from __future__ import annotations

import argparse


def lint_parser(subparsers=None):
    if subparsers is not None:
        parser = subparsers.add_parser("lint", help="Static TPU correctness checks (AST tier + selfcheck)")
    else:
        parser = argparse.ArgumentParser("accelerate-tpu lint")
    parser.add_argument("paths", nargs="*", help="Files or directories to lint (.py files)")
    parser.add_argument(
        "--changed", action="store_true",
        help="Lint only git-touched .py files (keeps make lint flat as tiers grow; "
        "falls back to the given paths without git)",
    )
    parser.add_argument("--format", choices=("text", "json", "sarif"), default=None, help="Report format")
    parser.add_argument("--select", default=None, help="Comma-separated rule IDs to run (default: all)")
    parser.add_argument("--ignore", default="", help="Comma-separated rule IDs to skip")
    parser.add_argument(
        "--lazy-jax",
        choices=("auto", "always", "never"),
        default="auto",
        help="TPU204 zone: enforce the _jax() lazy-import convention (default: auto-detect)",
    )
    parser.add_argument("--strict", action="store_true", help="Exit nonzero on warnings too")
    parser.add_argument(
        "--selfcheck",
        action="store_true",
        help="Run every rule against its seeded-defect fixture on a CPU fake mesh",
    )
    if subparsers is not None:
        parser.set_defaults(func=lint_command)
    return parser


def _split_ids(raw):
    return frozenset(p.strip().upper() for p in raw.split(",") if p.strip()) or None


def lint_command(args) -> int:
    from accelerate_tpu.analysis import LintConfig, exit_code, lint_paths, render_json, render_sarif, render_text
    from accelerate_tpu.analysis.project_config import load_project_config

    cfg = load_project_config()
    fmt = cfg.resolve_format(args.format)

    if not args.paths and not args.selfcheck and not args.changed:
        print("usage: accelerate-tpu lint [paths ...] [--changed] [--selfcheck]")
        return 2

    if args.changed:
        from accelerate_tpu.analysis.changed import changed_python_files

        scoped = changed_python_files()
        if scoped is None:
            import sys

            print("lint: --changed needs a git work tree; linting the full paths", file=sys.stderr)
        else:
            args.paths = scoped

    rc = 0
    if args.selfcheck:
        # the jaxpr fixtures need a (multi-device) mesh; never touch a real
        # backend from a lint invocation — same bootstrap as check_repo.py
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(8)
        from accelerate_tpu.analysis.selfcheck import run_selfcheck

        ok, lines = run_selfcheck()
        if fmt == "text":
            for line in lines:
                print(line)
        if not ok:
            print("selfcheck FAILED: a rule missed its seeded defect")
            return 1

    findings = []
    if args.paths:
        config = LintConfig(
            select=cfg.merge_select(_split_ids(args.select) if args.select else None),
            ignore=cfg.merge_ignore(_split_ids(args.ignore) or frozenset()),
            lazy_jax=args.lazy_jax,
        )
        findings = cfg.apply_suppressions(lint_paths(args.paths, config))
        rc = exit_code(findings, strict=args.strict)

    if fmt == "json":
        print(render_json(findings))
    elif fmt == "sarif":
        print(render_sarif(findings))
    elif findings or args.paths:
        print(render_text(findings))
    return rc


def main():
    raise SystemExit(lint_command(lint_parser().parse_args()))


if __name__ == "__main__":
    main()
