"""``accelerate-tpu telemetry`` — summarize a run's JSONL event log, or
self-check the whole runtime-telemetry pipeline on CPU.

``summarize`` parses a telemetry file (written by
``Accelerator.telemetry`` / :class:`~accelerate_tpu.telemetry.Telemetry`)
and renders step-time p50/p95, the data-wait/dispatch/execute split,
compile time, recompile count (with the changed avals), MFU, goodput,
HBM peak (observed + flight-check-predicted) and serving counters — no
TPU, no jax required to read.

``selfcheck`` runs a 5-step jitted loop on the CPU backend with the
watchdog armed (including a deliberate shape perturbation), writes the
JSONL, re-parses it, and asserts the summary holds what the docs promise
— the CI gate ``make telemetry-selfcheck`` wraps.

Examples::

    accelerate-tpu telemetry summarize run.jsonl
    accelerate-tpu telemetry summarize run.jsonl --format json
    accelerate-tpu telemetry selfcheck
"""

from __future__ import annotations

import argparse
import json
import os


def telemetry_parser(subparsers=None):
    if subparsers is not None:
        parser = subparsers.add_parser(
            "telemetry", help="Summarize or self-check runtime telemetry JSONL event logs"
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu telemetry")
    sub = parser.add_subparsers(dest="telemetry_command", required=True)

    p_sum = sub.add_parser("summarize", help="Render a telemetry JSONL file as a run report")
    p_sum.add_argument("path", help="telemetry JSONL file (e.g. runs/telemetry.jsonl)")
    p_sum.add_argument("--format", choices=("text", "json"), default="text", help="Report format")
    p_sum.add_argument(
        "--strict", action="store_true",
        help="Exit nonzero when the run recorded warnings (recompiles, HBM drift)",
    )
    p_sum.set_defaults(telemetry_func=summarize_command)

    p_check = sub.add_parser("selfcheck", help="Prove the telemetry pipeline works on the CPU backend")
    p_check.set_defaults(telemetry_func=selfcheck_command)

    if subparsers is not None:
        parser.set_defaults(func=lambda args: args.telemetry_func(args))
    return parser


def summarize_command(args) -> int:
    if not os.path.exists(args.path):
        print(f"no such file: {args.path}")
        return 2
    from accelerate_tpu.telemetry import render_text, summarize_file

    report = summarize_file(args.path)
    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        print(render_text(report))
    if args.strict and report.get("warnings"):
        return 1
    return 0


def selfcheck_command(args) -> int:
    """5-step CPU loop -> JSONL -> parse -> summarize; nonzero on any
    broken link in that chain."""
    import tempfile

    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(1)
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.telemetry import Telemetry, read_events, render_text, summarize

    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "run.jsonl")
        tel = Telemetry(
            path,
            rank=0,
            hbm_sample_every=1,
            flops_per_step=2 * 64 * 64 * 64,
            peak_flops_per_device=1e12,
        )
        step = tel.wrap(jax.jit(lambda x: (x @ x).sum()))
        x = jnp.ones((64, 64), jnp.float32)
        for _ in range(5):
            step(x)
        step(jnp.ones((32, 32), jnp.float32))  # post-warmup cache miss
        tel.close()

        events = read_events(path)
        if not events:
            failures.append("event log is empty or unparseable")
        if any(e.get("v") != 1 or "ts" not in e or "rank" not in e for e in events):
            failures.append("schema fields missing on some records")
        report = summarize(events)
        steps = report.get("steps") or {}
        if steps.get("count") != 6:
            failures.append(f"expected 6 step spans, got {steps.get('count')}")
        if steps.get("recompiles") != 1:
            failures.append(f"expected exactly 1 recompile, got {steps.get('recompiles')}")
        if steps.get("p50_step_ms") is None or steps.get("p95_step_ms") is None:
            failures.append("step-time percentiles missing")
        if steps.get("compile_ms", 0) <= 0:
            failures.append("compile attribution missing")
        print(render_text(report))

    for msg in failures:
        print(f"[telemetry selfcheck] FAILED: {msg}")
    if not failures:
        print("[telemetry selfcheck] OK: log schema, step split, watchdog, summarize")
    return 1 if failures else 0


def main():
    args = telemetry_parser().parse_args()
    raise SystemExit(args.telemetry_func(args))


if __name__ == "__main__":
    main()
