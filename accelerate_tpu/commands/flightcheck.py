"""``accelerate-tpu flight-check`` — static SPMD cost/safety analysis of a
step function before any XLA compile.

Points at a step function — ``path/to/file.py::fn`` or ``pkg.module:fn`` —
traces it abstractly against a mesh, and prints the flight report: peak
HBM per device, the collective traffic bill (bytes on wire, ICI vs DCN),
and the TPU3xx safety findings (collective deadlock under value-dependent
control flow, implicit reshards, defeated donation).

Sample shapes come from repeatable ``--arg dtype[shape]`` specs, or from
the target module itself: a ``SAMPLE_ARGS`` constant/callable, or a
``<fn>_sample_args`` function next to the step. Everything runs on the CPU
backend with a fake multi-device mesh — safe on a dev box with no TPU.

Examples::

    accelerate-tpu flight-check examples/by_feature/flight_check.py::train_step
    accelerate-tpu flight-check train.py::step --arg "f32[32,128]" --mesh data=4,tensor=2
    accelerate-tpu flight-check train.py::step --donate 0 --format json --hbm-gb 16
    accelerate-tpu flight-check --selfcheck        # prove TPU301/302/303 fire
"""

from __future__ import annotations

import argparse
import importlib
import os
import re
import sys

_DTYPE_ALIASES = {
    "f32": "float32", "f64": "float64", "f16": "float16", "bf16": "bfloat16",
    "i32": "int32", "i64": "int64", "i8": "int8", "u8": "uint8", "bool": "bool",
    "f8e4m3": "float8_e4m3fn", "f8e5m2": "float8_e5m2",
}

_ARG_RE = re.compile(r"^\s*([A-Za-z0-9_]+)\[([0-9,\s]*)\]\s*$")


def parse_arg_spec(spec: str):
    """``"f32[8,128]"`` -> ``jax.ShapeDtypeStruct((8, 128), float32)``."""
    import jax
    import jax.numpy as jnp

    m = _ARG_RE.match(spec)
    if m is None:
        raise ValueError(f"bad --arg spec {spec!r}; expected e.g. f32[8,128] or i32[16]")
    dtype = _DTYPE_ALIASES.get(m.group(1), m.group(1))
    shape = tuple(int(d) for d in m.group(2).split(",") if d.strip())
    return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))


def load_step(target: str):
    """Resolve ``file.py::fn`` or ``pkg.module:fn`` to ``(module, fn)``."""
    if "::" in target:
        path, _, fn_name = target.partition("::")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no such file: {path}")
        spec = importlib.util.spec_from_file_location(
            os.path.splitext(os.path.basename(path))[0], path
        )
        module = importlib.util.module_from_spec(spec)
        sys.path.insert(0, os.path.dirname(os.path.abspath(path)))
        try:
            spec.loader.exec_module(module)
        finally:
            sys.path.pop(0)
    elif ":" in target:
        mod_name, _, fn_name = target.partition(":")
        module = importlib.import_module(mod_name)
    else:
        raise ValueError(f"target {target!r} must be file.py::fn or pkg.module:fn")
    try:
        fn = getattr(module, fn_name)
    except AttributeError as e:
        raise AttributeError(f"{target!r}: module has no function {fn_name!r}") from e
    return module, fn


def resolve_sample_args(module, fn, arg_specs):
    """Sample args for the trace: explicit ``--arg`` specs win; else the
    module's ``<fn>_sample_args()`` / ``SAMPLE_ARGS`` convention."""
    if arg_specs:
        return tuple(parse_arg_spec(s) for s in arg_specs)
    builder = getattr(module, f"{fn.__name__}_sample_args", None) or getattr(module, "SAMPLE_ARGS", None)
    if builder is None:
        raise ValueError(
            f"no sample shapes for {fn.__name__}: pass --arg 'f32[8,128]' (repeatable) "
            f"or define {fn.__name__}_sample_args() / SAMPLE_ARGS in the module"
        )
    return tuple(builder()) if callable(builder) else tuple(builder)


def build_mesh(mesh_spec: str | None):
    """``"data=2,tensor=2"`` -> a fake CPU mesh of that shape (host
    platform forced before jax initialises). Default: all devices on
    ``data``."""
    from accelerate_tpu.parallel.mesh import MeshConfig

    kwargs = {}
    if mesh_spec:
        for part in mesh_spec.split(","):
            name, _, val = part.partition("=")
            kwargs[name.strip()] = int(val)
    n_needed = 1
    for v in kwargs.values():
        n_needed *= max(1, v)
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(max(8, n_needed))
    if not kwargs:
        return MeshConfig().build()
    import jax

    # explicit shapes may use fewer devices than the fake host platform has
    return MeshConfig(**kwargs).build(jax.devices()[:n_needed])


def flightcheck_parser(subparsers=None):
    if subparsers is not None:
        parser = subparsers.add_parser(
            "flight-check", help="Static peak-HBM / collective-cost / deadlock analysis of a step fn"
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu flight-check")
    parser.add_argument("target", nargs="?", help="step function: file.py::fn or pkg.module:fn")
    parser.add_argument("--arg", action="append", default=[], help="sample arg spec like f32[8,128] (repeatable)")
    parser.add_argument("--mesh", default=None, help="mesh shape, e.g. data=4,tensor=2 (default: all devices on data)")
    parser.add_argument("--donate", default="", help="comma-separated donated argnums, e.g. 0,1")
    parser.add_argument("--dcn-axes", default=None, help="axes that cross DCN, e.g. data (default: env/single-slice)")
    parser.add_argument("--generation", default="v5e", help="TPU generation for the bandwidth table (v4/v5e/v5p/v6e)")
    parser.add_argument("--hbm-gb", type=float, default=None, help="per-device HBM; adds a fits/doesn't-fit verdict")
    parser.add_argument("--format", choices=("text", "json", "sarif"), default=None, help="Report format")
    parser.add_argument("--strict", action="store_true", help="Exit nonzero on warnings too")
    parser.add_argument(
        "--selfcheck", action="store_true",
        help="Prove TPU301/302/303 fire on seeded defects (no target needed)",
    )
    if subparsers is not None:
        parser.set_defaults(func=flightcheck_command)
    return parser


def _selfcheck() -> int:
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(8)
    from accelerate_tpu.analysis.flightcheck import flight_check
    from accelerate_tpu.analysis.selfcheck import _flight_fixtures
    from accelerate_tpu.parallel.mesh import MeshConfig

    mesh = MeshConfig().build()
    ok = True
    for rule, (fn, args, kwargs) in sorted(_flight_fixtures(mesh).items()):
        report = flight_check(fn, *args, mesh=mesh, select=(rule,), **kwargs)
        fired = any(f.rule == rule for f in report.findings)
        ok &= fired
        print(f"[flight-check selfcheck] {rule}: {'detected' if fired else 'MISSED'}")
    if not ok:
        print("flight-check selfcheck FAILED: a rule missed its seeded defect")
        return 1
    return 0


def flightcheck_command(args) -> int:
    if args.selfcheck:
        rc = _selfcheck()
        if rc or not args.target:
            return rc

    if not args.target:
        print("usage: accelerate-tpu flight-check file.py::step_fn [--arg f32[8,128] ...]")
        return 2

    mesh = build_mesh(args.mesh)
    module, fn = load_step(args.target)
    sample_args = resolve_sample_args(module, fn, args.arg)
    donate = tuple(int(p) for p in args.donate.split(",") if p.strip())
    dcn = tuple(a.strip() for a in args.dcn_axes.split(",") if a.strip()) if args.dcn_axes else None

    from accelerate_tpu.analysis import exit_code, render_sarif
    from accelerate_tpu.analysis.flightcheck import flight_check
    from accelerate_tpu.analysis.project_config import load_project_config

    cfg = load_project_config()
    report = flight_check(
        fn, *sample_args, mesh=mesh, donate_argnums=donate, dcn=dcn, generation=args.generation,
        ignore=tuple(cfg.disable),
    )
    findings = cfg.apply_suppressions(report.findings)
    fmt = cfg.resolve_format(args.format)
    if fmt == "json":
        import json

        print(json.dumps(report.as_dict(), indent=2))
    elif fmt == "sarif":
        # same SARIF 2.1.0 reporter the lint CLI uses, so every analysis
        # tier can feed GitHub code scanning from one upload step
        print(render_sarif(findings))
    else:
        print(report.render_text())
        if args.hbm_gb is not None:
            verdict = "fits" if report.fits(args.hbm_gb) else "DOES NOT FIT"
            print(f"  verdict: {verdict} in {args.hbm_gb:g} GB/device HBM")
    return exit_code(findings, strict=args.strict)


def main():
    raise SystemExit(flightcheck_command(flightcheck_parser().parse_args()))


if __name__ == "__main__":
    main()
