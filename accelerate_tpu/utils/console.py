"""Console helper (reference analogue: utils/rich.py — a rich ``Console``
singleton used for ``--debug`` tracebacks, commands/launch.py:816-822).

``rich`` is optional; without it the shim degrades to plain ANSI color on a
tty and uncolored text otherwise, so CLI error reporting works on a bare
TPU VM."""

from __future__ import annotations

import sys
import traceback

_console = None


def get_console():
    """The process-wide console: ``rich.console.Console`` when available,
    else a minimal same-surface shim."""
    global _console
    if _console is None:
        try:
            from rich.console import Console

            _console = Console(stderr=True)
        except ImportError:
            _console = _PlainConsole()
    return _console


class _PlainConsole:
    """print/rule/print_exception subset of rich's Console."""

    def _color(self, code: str, text: str) -> str:
        if sys.stderr.isatty():
            return f"\033[{code}m{text}\033[0m"
        return text

    def print(self, *objects, style: str | None = None, **kwargs):
        text = " ".join(str(o) for o in objects)
        if style and "red" in style:
            text = self._color("31", text)
        elif style and "yellow" in style:
            text = self._color("33", text)
        print(text, file=sys.stderr)

    def rule(self, title: str = ""):
        width = 79
        pad = max(0, width - len(title) - 2)
        print(f"{'─' * (pad // 2)} {title} {'─' * (pad - pad // 2)}" if title else "─" * width, file=sys.stderr)

    def print_exception(self, **kwargs):
        traceback.print_exc(file=sys.stderr)


def print_launch_failure(rc: int, attempt: int | None = None):
    """Launcher-failure banner (reference: rich traceback on launch
    failure, commands/launch.py:816-822)."""
    console = get_console()
    console.rule("launch failed")
    msg = f"child process exited with code {rc}"
    if attempt is not None:
        msg += f" (attempt {attempt})"
    console.print(msg, style="bold red")
    console.print(
        "Re-run with --debug for collective shape verification, or "
        "ACCELERATE_LOG_LEVEL=debug for verbose logs.",
        style="yellow",
    )
