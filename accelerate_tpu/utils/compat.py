"""Cross-version jax compatibility shims.

The codebase targets the jax >= 0.6 API surface (``jax.shard_map`` with
``check_vma``, ``jax.sharding.AxisType`` / ``get_abstract_mesh``); these
adapters keep identical call sites running on jax 0.4.x, where shard_map
lives in ``jax.experimental`` with ``check_rep`` and meshes have no axis
types. Only behavior-preserving renames are adapted here — anything with
different semantics across versions does not belong in this module.

jax is imported lazily: this module sits under the package's eager import
path and must not initialise a backend.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` (0.6+ signature) with fallback to
    ``jax.experimental.shard_map.shard_map`` (``check_vma`` was named
    ``check_rep`` there — same meaning, per-value replication checking)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma)


def supports_memory_kind(kind: str = "pinned_host") -> bool:
    """Whether the default device can address ``kind`` memory. TPU backends
    expose ``pinned_host`` for optimizer-state offload; older CPU backends
    address only ``unpinned_host``, where offload must degrade gracefully
    instead of dying in ``NamedSharding.with_memory_kind``."""
    import jax

    try:
        return any(m.kind == kind for m in jax.devices()[0].addressable_memories())
    except Exception:
        return False


def in_manual_region() -> bool:
    """True when tracing inside a shard_map/pmap body — mesh axes are
    Manual there, and nesting another shard_map over the same mesh is an
    error, so sharded-dispatch wrappers must use the bare kernel. On new
    jax this reads the abstract mesh's axis types; on 0.4.x the bound
    axis env carries the same information."""
    import jax

    try:
        am = jax.sharding.get_abstract_mesh()
        manual = jax.sharding.AxisType.Manual
        return any(t == manual for t in getattr(am, "axis_types", ()))
    except AttributeError:
        pass
    try:
        from jax._src.core import get_axis_env

        return bool(get_axis_env().axis_sizes)
    except Exception:
        return False
