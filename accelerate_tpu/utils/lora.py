"""LoRA — low-rank adaptation as a functional transform on param pytrees.

The reference is PEFT-aware rather than PEFT-implementing (reference:
src/accelerate/utils/modeling.py:73 ``is_peft_model``, the kbit-training
prep in utils/bnb.py): torch users bring ``peft`` and Accelerate unwraps /
checkpoints around it. On TPU the idiomatic shape is different — params
are a pytree, so LoRA is a *pure function of trees*, not a module
surgery: ``lora_init`` builds an adapter tree mirroring the target
kernels, the train step merges ``W + (alpha/r)·A@B`` inside ``jit`` (XLA
fuses the add into the consumer matmul), and only the adapter tree is
trainable — the base params are frozen by construction, so the optimizer,
checkpointing, and every parallelism layout work on adapters unchanged.

Supports 2-D kernels and scan-stacked ``[L, in, out]`` kernels (the
``a @ b`` contraction broadcasts over leading layer dims).

**QLoRA** (reference: the bnb kbit-training prep in utils/bnb.py + PEFT's
4-bit fine-tune path): a ``QTensor`` base kernel is a first-class target.
The adapter pair is float (the QTensor's original dtype by default), the
packed codes stay frozen AND quantized in HBM, and the per-step merge is
``dequantize(W_q) + (alpha/r)·A@B`` inside ``jit`` — the dequantized copy
is transient (XLA fuses the decode+add into the consumer matmul), so
resident memory is codes + adapters + adapter optimizer state: the QLoRA
budget. Only the in-scan ``QuantDense`` rebuilt models (plain
``qdata``/``qscale`` array params, e.g. ``quantize_llama_model``) cannot
take adapters — their kernels are gone from the tree; use the generic
``quantize_params``/``load_and_quantize_model`` tree path for QLoRA.

Example::

    cfg = LoRAConfig(rank=8)
    adapters = lora_init(jax.random.key(0), model.params, cfg)
    def loss_fn(adapters, batch):
        params = lora_merge(model.params, adapters, cfg)
        return loss(model.apply_fn(params, **batch), batch["labels"])
    grads = jax.grad(loss_fn)(adapters, batch)       # adapters only
    merged = lora_merge(model.params, adapters, cfg) # export
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.sharding import path_str, spec_for_path
from .quantization import QTensor


def _is_q(leaf) -> bool:
    return isinstance(leaf, QTensor)


def _flatten_kernels(params):
    """Flatten with ``QTensor`` treated as ONE leaf at its kernel path (so a
    quantized kernel is targetable by the same regex as a dense one, rather
    than flattening into ``<kernel>/0``, ``/1`` data/scale children)."""
    return jax.tree_util.tree_flatten_with_path(params, is_leaf=_is_q)[0]

# classic LoRA targets: the attention q/v projections, across the zoo's
# two naming families (bert-style attention/query, llama-style attn/q_proj)
DEFAULT_TARGETS = r"(attention|attn)/(query|value|q_proj|v_proj)/kernel$"


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """What to adapt and how.

    ``targets`` is a regex matched (``re.search``) against ``/``-joined
    leaf paths, the same convention as sharding rules. ``alpha`` defaults
    to ``rank`` (scale 1.0, the PEFT default of r == lora_alpha).
    """

    rank: int = 8
    alpha: float | None = None
    targets: str = DEFAULT_TARGETS
    init_std: float = 0.02
    dtype: Any | None = None

    @property
    def scaling(self) -> float:
        return (self.alpha if self.alpha is not None else float(self.rank)) / self.rank


def _path_tuple(key_path) -> tuple[str, ...]:
    return tuple(path_str(key_path).split("/"))


def lora_targets(params: Any, config: LoRAConfig = LoRAConfig()) -> list[str]:
    """Paths in ``params`` the config will adapt (>=2-D leaves matching
    ``targets``)."""
    out = []
    for key_path, leaf in _flatten_kernels(params):
        path = path_str(key_path)
        ndim = len(leaf.shape) if _is_q(leaf) else getattr(leaf, "ndim", 0)
        if re.search(config.targets, path) and ndim >= 2:
            out.append(path)
    return out


def lora_init(rng, params: Any, config: LoRAConfig = LoRAConfig()) -> Any:
    """Build the trainable adapter tree.

    Mirrors ``params``' nesting, with each target kernel replaced by
    ``{"lora_a": [.., in, r], "lora_b": [.., r, out]}``. A is
    normal(init_std), B is zeros — so at init the adapted model computes
    exactly the base model. A ``QTensor`` target gets float adapters in its
    original dtype (QLoRA — the codes stay frozen+packed; see module
    docstring). Raises if nothing matches, or if a match is a plain
    integer leaf (an in-scan ``QuantDense`` model's ``qdata``).
    """
    adapters: dict = {}
    matched = False
    for key_path, leaf in _flatten_kernels(params):
        path = path_str(key_path)
        ndim = len(leaf.shape) if _is_q(leaf) else getattr(leaf, "ndim", 0)
        if not re.search(config.targets, path) or ndim < 2:
            # an in-scan QuantDense kernel is not in the tree: its codes are
            # plain `<layer>/qdata`, `/qscale` array params, so a target
            # regex naming the LAYER sees the parent path — detect and
            # refuse rather than silently skipping the layer
            quant_parent = re.sub(r"/(qdata|qscale|\d+)$", "", path)
            if quant_parent != path and re.search(config.targets, quant_parent):
                raise ValueError(
                    f"LoRA target {quant_parent!r} is an in-scan QuantDense layer — its "
                    "kernel exists only as packed qdata/qscale params, so adapters cannot "
                    "attach. For QLoRA, quantize with quantize_params/load_and_quantize_model "
                    "(QTensor tree) instead of the rebuilt-module path "
                    "(see docs/usage_guides/lora.md)."
                )
            continue
        if not _is_q(leaf) and not jnp.issubdtype(leaf.dtype, jnp.floating):
            raise ValueError(
                f"LoRA target {path!r} has dtype {leaf.dtype} — adapters cannot attach to "
                "raw integer codes. For QLoRA, quantize with quantize_params/"
                "load_and_quantize_model (QTensor tree) so the kernel stays a targetable "
                "leaf (see docs/usage_guides/lora.md)."
            )
        matched = True
        lead, in_dim, out_dim = leaf.shape[:-2], leaf.shape[-2], leaf.shape[-1]
        dtype = config.dtype or leaf.dtype
        rng, key = jax.random.split(rng)
        pair = {
            "lora_a": config.init_std * jax.random.normal(key, lead + (in_dim, config.rank), dtype),
            "lora_b": jnp.zeros(lead + (config.rank, out_dim), dtype),
        }
        node = adapters
        for part in _path_tuple(key_path):
            node = node.setdefault(part, {})
        node.update(pair)
    if not matched:
        sample = [path_str(kp) for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0][:8]]
        raise ValueError(f"LoRA targets {config.targets!r} matched no parameter; paths look like {sample}")
    return adapters


def _adapter_pairs(adapters: Any) -> dict[tuple[str, ...], dict]:
    """Flatten the adapter tree to {kernel-path-tuple: {"lora_a","lora_b"}}."""
    pairs: dict[tuple[str, ...], dict] = {}
    for key_path, leaf in jax.tree_util.tree_flatten_with_path(adapters)[0]:
        parts = _path_tuple(key_path)
        pairs.setdefault(parts[:-1], {})[parts[-1]] = leaf
    return pairs


def lora_merge(params: Any, adapters: Any, config: LoRAConfig) -> Any:
    """``W + scaling * A @ B`` on every adapted kernel; other leaves pass
    through untouched. Safe inside ``jit`` — this is the per-step path
    (XLA fuses the add), and also the export path (``merge_and_unload``).

    ``config`` is required because it carries the merge scale
    (``alpha/rank``): merging with a default config would silently
    mis-scale adapters trained with ``alpha != rank``. Use the config you
    trained with, or the one :func:`load_lora` returns.
    """
    pairs = _adapter_pairs(adapters)

    def merge_leaf(key_path, leaf):
        pair = pairs.get(_path_tuple(key_path))
        if pair is None:
            return leaf
        delta = jnp.matmul(pair["lora_a"], pair["lora_b"]) * config.scaling
        if _is_q(leaf):
            # QLoRA merge: decode the frozen codes (a constant — gradients
            # flow only through delta) and add. Inside jit the decoded copy
            # is transient (fused into the consumer matmul); on export this
            # IS the dense merged weight — re-quantize it if you want a
            # quantized serving artifact.
            return (leaf.dequantize(jnp.float32) + delta.astype(jnp.float32)).astype(leaf.dtype)
        return (leaf + delta).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(merge_leaf, params, is_leaf=_is_q)


merge_and_unload = lora_merge


def lora_num_params(params: Any, adapters: Any) -> tuple[int, int, float]:
    """(trainable, total, trainable %) — the PEFT ``print_trainable_parameters`` numbers."""
    trainable = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(adapters))
    # QTensor counts its LOGICAL element count (shape is the original shape)
    total = sum(
        int(np.prod(x.shape))
        for x in jax.tree_util.tree_leaves(params, is_leaf=_is_q)
    )
    return trainable, total, 100.0 * trainable / max(total + trainable, 1)


def _derived_spec(parts: tuple[str, ...], leaf_ndim: int, base_spec) -> PartitionSpec:
    """The adapter spec for one A/B leaf from its base kernel's spec:
    A inherits the kernel's input-dim sharding (rank dim replicated),
    B its output-dim sharding — so under tensor parallelism ``A @ B``
    lands sharded exactly like ``W`` and the merge add needs no
    resharding. Shared by :func:`lora_shardings` and
    :func:`lora_adapter_rules` so the derivation cannot diverge."""
    base = list(tuple(base_spec)) + [None] * (leaf_ndim - len(tuple(base_spec)))
    if parts[-1] == "lora_a":
        spec = base[:-1] + [None]
    else:
        spec = base[:-2] + [None, base[-1]]
    return PartitionSpec(*spec)


def lora_shardings(adapters: Any, rules, mesh) -> Any:
    """``NamedSharding`` tree for the adapters, derived from the BASE
    kernel's rule (see :func:`_derived_spec`)."""

    def to_sharding(key_path, leaf):
        parts = _path_tuple(key_path)
        base_spec = spec_for_path("/".join(parts[:-1]), rules) or PartitionSpec()
        spec = _derived_spec(parts, leaf.ndim, base_spec)
        spec = PartitionSpec(*(s if s is None or s in mesh.axis_names else None for s in tuple(spec)))
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(to_sharding, adapters)


def lora_adapter_rules(adapters: Any, base_rules, base_specs: Optional[dict] = None) -> list:
    """Exact ``(regex, PartitionSpec)`` rules for an adapter tree —
    one fully-anchored (``^...$``) rule per concrete leaf path, so they
    drop into the rules engine and cannot shadow sibling paths. The base
    kernel's spec comes from ``base_specs`` (a ``{kernel-path: spec}``
    map of the base's ACTUAL placements, e.g. from a prepared model's
    ``param_shardings`` — this captures fsdp auto-rules the regex rules
    don't carry) with ``base_rules`` as the fallback. This is what lets
    :func:`lora_model` ride ``Accelerator.prepare``.
    """
    rules = []
    for key_path, leaf in jax.tree_util.tree_flatten_with_path(adapters)[0]:
        parts = _path_tuple(key_path)
        parent = "/".join(parts[:-1])
        base_spec = (base_specs or {}).get(parent)
        if base_spec is None:
            base_spec = spec_for_path(parent, base_rules) or PartitionSpec()
        spec = _derived_spec(parts, leaf.ndim, base_spec)
        rules.append(("^" + re.escape("/".join(parts)) + "$", spec))
    return rules


def lora_model(model, config: LoRAConfig = LoRAConfig(), rng=None):
    """Wrap a zoo ``Model`` so its trainable params ARE the adapter tree.

    The returned Model's ``apply_fn(adapters, ...)`` merges into the
    frozen base inside the call, so the whole Accelerator stack —
    ``prepare`` (adapter shardings derived from the base rules),
    ``build_train_step``, ``save_state`` (adapter-only checkpoints,
    the PEFT pattern), trackers — works on adapters with zero special
    casing. Prepare the BASE model first if it should be sharded; its
    current placement is captured as the frozen closure.

        model = accelerator.prepare_model(create_bert_model(cfg))
        lora = lora_model(model, LoRAConfig(rank=8))
        lora = accelerator.prepare_model(lora)     # shards the adapters
        step = accelerator.build_train_step(loss_fn)   # trains adapters only
    """
    from ..modeling import Model

    rng = jax.random.key(0) if rng is None else rng
    adapters = lora_init(rng, model.params, config)
    base = model.params

    def apply_fn(ad, *args, **kwargs):
        return model.apply_fn(lora_merge(base, ad, config), *args, **kwargs)

    def eval_apply_fn(ad, *args, **kwargs):
        return model.eval_apply_fn(lora_merge(base, ad, config), *args, **kwargs)

    # prefer the base's ACTUAL placements (set by prepare_model) over its
    # regex rules — a prepared base may carry fsdp auto-shardings the
    # rules don't express, and the adapters must match W's real layout
    base_specs = None
    if getattr(model, "param_shardings", None) is not None:
        base_specs = {
            path_str(kp): sh.spec
            for kp, sh in jax.tree_util.tree_flatten_with_path(model.param_shardings)[0]
            if hasattr(sh, "spec")
        }

    wrapped = Model(
        apply_fn,
        adapters,
        sharding_rules=lora_adapter_rules(adapters, model.sharding_rules or [], base_specs),
        name=f"{model.name}+lora",
        eval_apply_fn=eval_apply_fn,
    )
    wrapped.state = model.state  # non-trainable collections ride along
    wrapped.config = getattr(model, "config", None)
    wrapped.lora_config = config
    wrapped.base_model = model
    wrapped.merged_params = lambda: lora_merge(base, wrapped.params, config)
    return wrapped


def save_lora(adapters: Any, path: str, config: LoRAConfig = LoRAConfig()) -> None:
    """Adapters + their config to one ``.npz`` keyed by ``/``-joined paths
    (the adapter tree is small; no need for sharded orbax here). The
    config rides along so the merge scale (alpha/rank) and target regex
    survive the round-trip — merging reloaded adapters with a default
    config would silently mis-scale the delta."""
    flat = {
        path_str(kp): np.asarray(leaf)
        for kp, leaf in jax.tree_util.tree_flatten_with_path(adapters)[0]
    }
    flat["__lora_rank__"] = np.asarray(config.rank)
    flat["__lora_alpha__"] = np.asarray(np.nan if config.alpha is None else config.alpha)
    flat["__lora_targets__"] = np.asarray(config.targets)
    np.savez(path, **flat)


def load_lora(path: str) -> tuple[Any, LoRAConfig]:
    """Returns ``(adapters, config)`` — pass both to :func:`lora_merge`."""
    with np.load(path) as data:
        alpha = float(data["__lora_alpha__"]) if "__lora_alpha__" in data.files else None
        config = LoRAConfig(
            rank=int(data["__lora_rank__"]) if "__lora_rank__" in data.files else 8,
            alpha=None if alpha is None or np.isnan(alpha) else alpha,
            targets=str(data["__lora_targets__"]) if "__lora_targets__" in data.files else DEFAULT_TARGETS,
        )
        adapters: dict = {}
        for key in data.files:
            if key.startswith("__lora_"):
                continue
            node = adapters
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = jnp.asarray(data[key])
    return adapters, config
