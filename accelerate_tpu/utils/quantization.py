"""Weight-only quantization (int8 / int4 / nf4) and fp8 compute helpers.

Reference parity: bitsandbytes integration — ``BnbQuantizationConfig``
(reference: src/accelerate/utils/dataclasses.py:2663) and
``load_and_quantize_model`` + layer replacement (reference:
src/accelerate/utils/bnb.py:44,276-373); fp8 torchao/transformer-engine
backends (reference: src/accelerate/utils/ao.py:104,
utils/transformer_engine.py:26-163).

TPU-native design — no CUDA kernels, no module surgery:

* a quantized weight is a :class:`QTensor` pytree leaf: packed integer data
  + per-(group, output-channel) scales. It flows through ``jit``/``jax.tree``
  like any array, halves (int8) or quarters (int4) HBM bytes, and XLA fuses
  the dequantize into the consuming matmul — the memory-bound decode win the
  reference gets from bnb's fused kernels.
* symmetric linear quant for int8/int4; the QLoRA NF4 codebook for nf4
  (information-theoretically optimal for ~normal weights).
* scales reduce over the **contraction** dim (axis -2 of ``[..., in, out]``
  kernels), so per-channel quantized matmul can apply scales *after* the
  int8 matmul — contraction and scaling commute.
* fp8: per-tensor dynamic scaling to ``float8_e4m3fn`` with a scaled
  ``dot_general`` — the TE "recipe" collapses to one function.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

# nf4 decode is a 16-entry codebook gather; at GB scale that gather
# KERNEL-FAULTS the TPU worker (measured on v5e: the XLA gather path crashes
# the runtime outright — worse than slow, unrecoverable). Guard every nf4
# decode on TPU: per-leaf at trace time (grouped_dequantize) and aggregate
# at quantize time (quantize_params), raising an actionable error pointing
# at int4 (whose Pallas fused dequant-matmul is measured FASTER than nf4
# could be, ops/pallas_qmatmul.py) long before the faulting op runs.
# Override (at your own risk) via ACCELERATE_NF4_MAX_ELEMENTS.
_NF4_DEFAULT_MAX_ELEMENTS = 2**26  # 67M decoded elements per tensor


def _nf4_max_elements() -> int:
    return int(os.environ.get("ACCELERATE_NF4_MAX_ELEMENTS", _NF4_DEFAULT_MAX_ELEMENTS))


def _nf4_guard(n_elements: int, what: str):
    if jax.default_backend() != "tpu":
        return
    limit = _nf4_max_elements()
    if n_elements > limit:
        raise ValueError(
            f"nf4 {what} of {n_elements:,} elements exceeds the TPU safety limit "
            f"({limit:,}): the XLA 16-entry-codebook gather kernel-faults the TPU "
            f"worker at this scale. Use method='int4' (grouped; Pallas fused "
            f"dequant-matmul, same accuracy envelope and faster) or 'int8'. "
            f"If you must, raise ACCELERATE_NF4_MAX_ELEMENTS."
        )

# QLoRA NF4 codebook (16 quantiles of N(0,1), normalised to [-1, 1]).
NF4_CODE = np.array(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)


@dataclass
class QuantizationConfig:
    """What to quantize and how (reference: BnbQuantizationConfig,
    utils/dataclasses.py:2663 — load_in_8bit/load_in_4bit/quant type/
    skip_modules map to bits/method/skip_patterns)."""

    bits: int = 8  # 8 or 4
    # "int8" (weight-only, bf16 activations) | "w8a8" (int8 activations too:
    # the matmul runs natively on the int8 MXU path — no per-weight convert,
    # so decode reaches HBM-bandwidth-bound) | "int4" | "nf4"
    method: Optional[str] = None  # default by bits
    group_size: Optional[int] = None  # None = one scale per output channel
    compute_dtype: str = "bfloat16"
    # leaves whose path matches any pattern stay un-quantized (the reference
    # keeps lm_head / skip_modules in fp16: utils/bnb.py:64-77)
    skip_patterns: tuple = ("embed", "lm_head", "norm", "bias", "scale")
    min_size: int = 4096  # don't bother with tiny leaves

    def __post_init__(self):
        if self.bits not in (8, 4):
            raise ValueError(f"bits must be 8 or 4, got {self.bits}")
        if self.method is None:
            self.method = "int8" if self.bits == 8 else "nf4"
        if self.method not in ("int8", "w8a8", "int4", "nf4"):
            raise ValueError(f"method must be int8|w8a8|int4|nf4, got {self.method!r}")
        if self.method not in ("int8", "w8a8") and self.bits != 4:
            self.bits = 4
        elif self.method in ("int8", "w8a8") and self.bits != 8:
            # int8 stores unpacked 8-bit codes; bits=4 would give no saving
            raise ValueError(
                f'method="{self.method}" requires bits=8; use method="int4"/"nf4" for 4-bit'
            )
        if self.method == "w8a8" and self.group_size is not None:
            # the native int8-MXU path needs per-channel scales (the scale
            # must commute past the whole contraction); grouped w8a8 would
            # silently degrade to the W8A16 dequantize path
            raise ValueError('method="w8a8" requires group_size=None (per-channel scales)')


@jax.tree_util.register_pytree_node_class
@dataclass
class QTensor:
    """A quantized array: packed integer ``data`` + broadcastable ``scale``.
    Pytree children are (data, scale) so it moves through jit/device_put/
    tree.map transparently; shape/dtype/method are static aux data."""

    data: jax.Array  # int8 codes; for 4-bit, two codes packed per byte along axis -2
    scale: jax.Array
    shape: tuple  # original shape
    dtype: Any  # original dtype
    method: str
    group_size: Optional[int]

    def tree_flatten(self):
        return (self.data, self.scale), (self.shape, self.dtype, self.method, self.group_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize + self.scale.size * self.scale.dtype.itemsize)

    def dequantize(self, dtype=None) -> jax.Array:
        return dequantize(self, dtype)


def _grouped(x: jax.Array, group_size: Optional[int]):
    """Reshape [..., in, out] so axis -3 indexes groups of the contraction
    dim: [..., n_groups, g, out]."""
    n_in = x.shape[-2]
    g = n_in if group_size is None else group_size
    if n_in % g != 0:
        raise ValueError(f"contraction dim {n_in} not divisible by group_size {g}")
    return x.reshape(*x.shape[:-2], n_in // g, g, x.shape[-1]), g


def quantize(x: jax.Array, config: QuantizationConfig) -> QTensor:
    """Quantize one array. 1D arrays are treated as [in, 1]."""
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    if x.ndim < 2:
        x = x[:, None]
    xg, g = _grouped(x.astype(jnp.float32), config.group_size)
    absmax = jnp.max(jnp.abs(xg), axis=-2, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12)

    if config.method in ("int8", "w8a8"):
        q = jnp.clip(jnp.round(xg / scale * 127.0), -127, 127).astype(jnp.int8)
        scale = scale / 127.0
    elif config.method == "int4":
        q = jnp.clip(jnp.round(xg / scale * 7.0), -7, 7).astype(jnp.int8)
        scale = scale / 7.0
        q = _pack4(q + 8)  # store as unsigned nibbles
    else:  # nf4
        norm = xg / scale
        # nearest-code lookup via searchsorted over the midpoints between
        # adjacent (sorted) codes: O(log 16) compares and no [..., 16]
        # broadcast — an argmin over the codebook materialises a 16x copy
        # of the weight tensor, which OOMs HBM on GB-scale conversions
        mids = jnp.asarray((NF4_CODE[1:] + NF4_CODE[:-1]) / 2.0)
        idx = jnp.searchsorted(mids, norm).astype(jnp.int8)
        q = _pack4(idx)
    return QTensor(q, scale.astype(jnp.float32), orig_shape, orig_dtype, config.method, config.group_size)


def grouped_dequantize(data: jax.Array, scale: jax.Array, method: str) -> jax.Array:
    """Decode grouped codes ``[..., n_groups, g(, packed), out]`` + scales to
    float ``[..., n_groups, g, out]`` — the single copy of the per-method
    decode used by :func:`dequantize` and the in-scan ``QuantDense``."""
    if method in ("int8", "w8a8"):
        return data.astype(jnp.float32) * scale
    if method == "int4":
        return (_unpack4(data).astype(jnp.float32) - 8.0) * scale
    if method == "nf4":
        codes = _unpack4(data)
        _nf4_guard(int(np.prod(codes.shape)), "decode")
        return jnp.asarray(NF4_CODE)[codes] * scale
    raise ValueError(f"method must be int8|int4|nf4, got {method!r}")


def dequantize(qt: QTensor, dtype=None) -> jax.Array:
    dtype = dtype or qt.dtype
    xg = grouped_dequantize(qt.data, qt.scale, qt.method)
    x = xg.reshape(*xg.shape[:-3], xg.shape[-3] * xg.shape[-2], xg.shape[-1])
    return x.reshape(qt.shape).astype(dtype)


def _pack4(codes: jax.Array) -> jax.Array:
    """Pack unsigned 4-bit codes pairwise along axis -2 (the group dim; group
    sizes are powers of two in practice, so it's even)."""
    if codes.shape[-2] % 2 != 0:
        raise ValueError(f"group size {codes.shape[-2]} must be even for 4-bit packing")
    lo, hi = codes[..., 0::2, :], codes[..., 1::2, :]
    return (lo.astype(jnp.uint8) | (hi.astype(jnp.uint8) << 4)).astype(jnp.uint8)


def _unpack4(packed: jax.Array) -> jax.Array:
    lo = (packed & 0x0F).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    out = jnp.stack([lo, hi], axis=-2)  # [..., n/2, 2, out]
    return out.reshape(*packed.shape[:-2], packed.shape[-2] * 2, packed.shape[-1])


def quantized_matmul(x: jax.Array, qt: QTensor) -> jax.Array:
    """``x @ W`` with a quantized ``W`` ([in, out] or stacked [..., in, out]).

    Per-channel int8 uses the commuting fast path (int matmul, scale after);
    grouped / 4-bit weights dequantize first — XLA fuses the dequant into
    the matmul so no full-precision copy of W persists in HBM."""
    if qt.method == "int8" and qt.group_size is None and len(qt.shape) == 2:
        y = jax.lax.dot_general(
            x.astype(jnp.bfloat16),
            qt.data.reshape(qt.shape).astype(jnp.bfloat16),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * qt.scale.reshape(1, -1)).astype(x.dtype)
    return x @ dequantize(qt, x.dtype)


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def quantize_params(params: Any, config: Optional[QuantizationConfig] = None) -> Any:
    """Quantize every matching leaf of a param pytree (>=2D, big enough,
    path not skipped). Returns a tree with QTensor leaves mixed in."""
    config = config or QuantizationConfig()
    skip = [re.compile(p) for p in config.skip_patterns]

    def eligible(path, leaf):
        return (
            hasattr(leaf, "ndim")
            and leaf.ndim >= 2
            and leaf.size >= config.min_size
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and not any(p.search(_path_str(path)) for p in skip)
        )

    if config.method == "nf4":
        # the generic wrapped apply (load_and_quantize_model fallback)
        # decodes EVERY leaf inside one program per forward — guard the
        # aggregate before quantizing, not at first run
        total = sum(
            int(leaf.size)
            for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
            if eligible(path, leaf)
        )
        _nf4_guard(total, "model decode (all leaves per forward)")

    def maybe_q(path, leaf):
        return quantize(leaf, config) if eligible(path, leaf) else leaf

    return jax.tree_util.tree_map_with_path(maybe_q, params)


def dequantize_params(params: Any, dtype=None) -> Any:
    return jax.tree.map(
        lambda l: dequantize(l, dtype) if isinstance(l, QTensor) else l,
        params,
        is_leaf=lambda l: isinstance(l, QTensor),
    )


def quantized_bytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(params, is_leaf=lambda l: isinstance(l, QTensor)):
        total += leaf.nbytes if isinstance(leaf, QTensor) else getattr(leaf, "nbytes", 0)
    return int(total)


def load_and_quantize_model(model, config: Optional[QuantizationConfig] = None):
    """Quantize a :class:`~accelerate_tpu.modeling.Model`'s params in place of
    the fp copies (API parity: reference utils/bnb.py:44).

    Zoo models that support it (llama family) are rebuilt with in-scan
    ``QuantDense`` layers — the packed codes are the params, dequant runs
    per layer inside the scan, and decode HBM traffic drops to the packed
    bytes. Other models fall back to a wrapped ``apply_fn`` that
    dequantizes the tree on the fly inside jit."""
    from ..modeling import Model

    config = config or QuantizationConfig()
    cfg_obj = getattr(model, "config", None)
    if cfg_obj is not None and hasattr(cfg_obj, "quant_method") and getattr(model, "module", None) is not None:
        from ..models.llama import quantize_llama_model

        return quantize_llama_model(model, config)
    qparams = quantize_params(model.params, config)
    dtype = jnp.dtype(config.compute_dtype)
    base_apply = model.apply_fn

    def apply_fn(p, *args, **kwargs):
        return base_apply(dequantize_params(p, dtype), *args, **kwargs)

    q = Model(apply_fn, qparams, sharding_rules=getattr(model, "sharding_rules", None), name=getattr(model, "name", None))
    for attr in ("config", "module"):
        if hasattr(model, attr):
            setattr(q, attr, getattr(model, attr))
    return q


# ---------------------------------------------------------------------------
# fp8 (per-tensor dynamic scaling — the TE/AO recipe collapsed to functions)
# ---------------------------------------------------------------------------

FP8_E4M3_MAX = 448.0
FP8_E5M2_MAX = 57344.0


def fp8_quantize(x: jax.Array, dtype=jnp.float8_e4m3fn):
    """Scale to the fp8 representable range: returns (x_fp8, inv_scale) with
    ``x ~= x_fp8 * inv_scale``."""
    fmax = FP8_E4M3_MAX if dtype == jnp.float8_e4m3fn else FP8_E5M2_MAX
    amax = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))), 1e-12)
    scale = fmax / amax
    return (x.astype(jnp.float32) * scale).astype(dtype), (1.0 / scale).astype(jnp.float32)


def fp8_dot(a: jax.Array, b: jax.Array, out_dtype=jnp.bfloat16) -> jax.Array:
    """``a @ b`` computed in fp8 (e4m3 inputs, fp32 accumulation) with
    per-tensor dynamic scales — the hot-path op behind the fp8 mixed
    precision mode (reference fp8 backends: SURVEY §2.6). Delegates to the
    custom-VJP matmul in :mod:`..ops.fp8` (single copy of the recipe)."""
    from ..ops.fp8 import _fp8_matmul

    return _fp8_matmul(a, b).astype(out_dtype)
