"""Disk/host offload for over-HBM weights.

Reference analogue: src/accelerate/utils/offload.py (213 LoC —
``OffloadedWeightsLoader`` lazy mapping :127, ``offload_state_dict`` :85,
numpy memmap writes :25). Same design: weights live in individual ``.dat``
memmaps (or safetensors) with a JSON index; reads are lazy and zero-copy
until device transfer.
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from typing import Optional

import numpy as np


def offload_weight(weight, weight_name: str, offload_folder: str, index: Optional[dict] = None) -> dict:
    """(reference: utils/offload.py:25)."""
    weight = np.asarray(weight)
    dtype = str(weight.dtype)
    array_path = os.path.join(offload_folder, f"{weight_name}.dat")
    os.makedirs(os.path.dirname(array_path), exist_ok=True)  # names may contain '/'
    if index is not None:
        index[weight_name] = {"dtype": dtype, "shape": list(weight.shape)}
    if weight.ndim == 0:
        weight = weight[None]
    mm = np.memmap(array_path, dtype=weight.dtype, mode="w+", shape=weight.shape)
    mm[:] = weight[:]
    mm.flush()
    return index


def load_offloaded_weight(weight_file: str, weight_info: dict):
    """(reference: utils/offload.py:50)."""
    shape = tuple(weight_info["shape"])
    if len(shape) == 0:
        return np.memmap(weight_file, dtype=weight_info["dtype"], mode="r", shape=(1,))[0]
    return np.memmap(weight_file, dtype=weight_info["dtype"], mode="r", shape=shape)


def offload_state_dict(save_dir: str, state_dict: Mapping) -> None:
    """(reference: utils/offload.py:85)."""
    os.makedirs(save_dir, exist_ok=True)
    index = {}
    for name, weight in state_dict.items():
        index = offload_weight(weight, name, save_dir, index)
    with open(os.path.join(save_dir, "index.json"), "w") as f:
        json.dump(index, f, indent=2)


class OffloadedWeightsLoader(Mapping):
    """Lazy ``{name: array}`` over memmap .dat files and/or safetensors
    shards (reference: utils/offload.py:127)."""

    def __init__(self, state_dict: Optional[dict] = None, save_folder: Optional[str] = None):
        if state_dict is None and save_folder is None:
            raise ValueError("need state_dict and/or save_folder")
        self.state_dict = dict(state_dict or {})
        self.save_folder = save_folder
        self.index = {}
        if save_folder is not None:
            index_path = os.path.join(save_folder, "index.json")
            if os.path.isfile(index_path):
                with open(index_path) as f:
                    self.index = json.load(f)
        self.all_keys = list(self.state_dict) + [k for k in self.index if k not in self.state_dict]

    def __getitem__(self, key: str):
        if key in self.state_dict:
            return self.state_dict[key]
        info = self.index[key]
        if "safetensors_file" in info:
            from safetensors.numpy import load_file

            return load_file(info["safetensors_file"])[info.get("weight_name", key)]
        return load_offloaded_weight(os.path.join(self.save_folder, f"{key}.dat"), info)

    def __iter__(self):
        return iter(self.all_keys)

    def __len__(self):
        return len(self.all_keys)
