"""Progress bars gated to the local main process
(reference: src/accelerate/utils/tqdm.py:25-43)."""

from __future__ import annotations

from .imports import is_tqdm_available


def tqdm(*args, main_process_only: bool = True, **kwargs):
    if not is_tqdm_available():
        raise ImportError("tqdm is required; install tqdm")
    import tqdm as _tqdm

    from ..state import PartialState

    if len(args) > 0 and isinstance(args[0], bool):
        raise ValueError(
            "Passing `True`/`False` positionally is deprecated; use `main_process_only=` instead."
        )
    disable = kwargs.pop("disable", False)
    if main_process_only and not disable:
        disable = not PartialState().is_local_main_process
    return _tqdm.tqdm(*args, disable=disable, **kwargs)
