"""Availability probes for optional dependencies.

Role of the reference's ``utils/imports.py`` (reference:
src/accelerate/utils/imports.py:50-300): cheap, cached ``is_*_available()``
checks that gate optional integrations (trackers, torch interop, datasets).
The probe list is TPU-native: JAX-stack packages are the core, torch is an
*optional* interop dependency (checkpoint import only), CUDA probes are gone.
"""

from __future__ import annotations

import functools
import importlib.metadata
import importlib.util


@functools.lru_cache(maxsize=None)
def _package_available(pkg_name: str) -> bool:
    return importlib.util.find_spec(pkg_name) is not None


def package_version(pkg_name: str) -> str | None:
    try:
        return importlib.metadata.version(pkg_name)
    except importlib.metadata.PackageNotFoundError:
        return None


def is_jax_available() -> bool:
    return _package_available("jax")


def is_flax_available() -> bool:
    return _package_available("flax")


def is_optax_available() -> bool:
    return _package_available("optax")


def is_orbax_available() -> bool:
    return _package_available("orbax")


def is_chex_available() -> bool:
    return _package_available("chex")


def is_torch_available() -> bool:
    return _package_available("torch")


def is_safetensors_available() -> bool:
    return _package_available("safetensors")


def is_transformers_available() -> bool:
    return _package_available("transformers")


def is_datasets_available() -> bool:
    return _package_available("datasets")


def is_einops_available() -> bool:
    return _package_available("einops")


def is_numpy_available() -> bool:
    return _package_available("numpy")


def is_pandas_available() -> bool:
    return _package_available("pandas")


def is_rich_available() -> bool:
    return _package_available("rich")


def is_tqdm_available() -> bool:
    return _package_available("tqdm")


def is_psutil_available() -> bool:
    return _package_available("psutil")


# ---------------------------------------------------------------------------
# Tracker probes (reference: utils/imports.py tracker section; tracking.py)
# ---------------------------------------------------------------------------

def is_tensorboard_available() -> bool:
    return (
        _package_available("tensorboardX")
        or _package_available("tensorboard")
        or _package_available("torch")  # torch ships torch.utils.tensorboard
    )


def is_wandb_available() -> bool:
    return _package_available("wandb")


def is_mlflow_available() -> bool:
    return _package_available("mlflow")


def is_comet_ml_available() -> bool:
    return _package_available("comet_ml")


def is_aim_available() -> bool:
    return _package_available("aim")


def is_clearml_available() -> bool:
    return _package_available("clearml")


def is_dvclive_available() -> bool:
    return _package_available("dvclive")


def is_swanlab_available() -> bool:
    return _package_available("swanlab")


def is_trackio_available() -> bool:
    return _package_available("trackio")


# ---------------------------------------------------------------------------
# Hardware probes
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def is_tpu_available() -> bool:
    """True when a real TPU backend is attached to this process."""
    if not is_jax_available():
        return False
    import jax

    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def is_multihost() -> bool:
    if not is_jax_available():
        return False
    import jax

    return jax.process_count() > 1
