"""Structure-preserving operations on pytrees of arrays + host-level
collectives.

Reference analogue: src/accelerate/utils/operations.py (866 LoC). Two big
semantic shifts on TPU:

* **In-program collectives don't live here.** Inside ``jit``, XLA inserts
  ``psum``/``all_gather`` from shardings; explicit in-jit collectives are in
  :mod:`accelerate_tpu.parallel.collectives` (for ``shard_map`` bodies).
  This module is the *host-level* layer: cross-process gathers for metrics,
  object broadcast, input padding — the reference's
  ``gather``/``broadcast``/``reduce``/``pad_across_processes``
  (operations.py:418-760) at the process boundary.

* **"Per-process tensor" becomes "global array".** One JAX process drives
  many chips and dataloaders hand out *global* ``jax.Array``s, so ``gather``
  means "materialise the full value on host" (multihost: DCN allgather).

The debug-mode operation verifier (reference: operations.py:363-395) is kept:
with ``ACCELERATE_DEBUG_MODE=1`` every collective first gathers per-process
shapes and raises :class:`DistributedOperationException` with a per-process
report on mismatch.
"""

from __future__ import annotations

import functools
import pickle
from typing import Any, Callable

import numpy as np


def _jax():
    import jax

    return jax


class DistributedOperationException(Exception):
    """Raised by debug-mode verification when per-process inputs mismatch
    (reference: utils/operations.py DistributedOperationException)."""


def is_array_like(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable = is_array_like,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to every leaf of ``data`` passing ``test_type``
    (reference: operations.py:84). Thin shim over ``jax.tree_util`` keeping
    the reference's name and error contract."""
    jax = _jax()

    def apply(leaf):
        if test_type(leaf):
            return func(leaf, *args, **kwargs)
        if error_on_other_type:
            raise TypeError(f"Unsupported type {type(leaf)} passed to {getattr(func, '__name__', func)}")
        return leaf

    return jax.tree_util.tree_map(apply, data)


def send_to_device(tensor: Any, device=None, non_blocking: bool = True, skip_keys=None):
    """Move a pytree onto device(s) (reference: operations.py:135).

    ``device`` may be a ``jax.Device``, a ``Sharding``, or None (default
    device). ``device_put`` is always async; ``non_blocking`` kept for parity.
    """
    jax = _jax()

    def put(leaf):
        if not is_array_like(leaf):
            return leaf
        return jax.device_put(leaf, device)

    if skip_keys and isinstance(tensor, dict):
        return type(tensor)(
            {k: (v if k in skip_keys else send_to_device(v, device)) for k, v in tensor.items()}
        )
    return jax.tree_util.tree_map(put, tensor)


def get_data_structure(data):
    """Shape/dtype skeleton of a pytree (reference: operations.py:184)."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype) if is_array_like(x) else x, data
    )


def find_batch_size(data) -> int | None:
    """Leading dim of the first array leaf (reference: operations.py:233)."""
    jax = _jax()
    for leaf in jax.tree_util.tree_leaves(data):
        if is_array_like(leaf) and len(leaf.shape) >= 1:
            return leaf.shape[0]
    return None


def slice_tensors(data, tensor_slice, process_index=None, num_processes=None):
    """Slice every array leaf (reference: operations.py:558)."""
    return recursively_apply(lambda x: x[tensor_slice], data)


def concatenate(data: list, dim: int = 0):
    """Concatenate matching pytrees leaf-wise (reference: operations.py:600)."""
    jax = _jax()
    first = data[0]
    if isinstance(first, (list, tuple)):
        return type(first)(concatenate([d[i] for d in data], dim=dim) for i in range(len(first)))
    if isinstance(first, dict):
        return type(first)({k: concatenate([d[k] for d in data], dim=dim) for k in first})
    if not is_array_like(first):
        raise TypeError(f"Can only concatenate arrays/dicts/lists, got {type(first)}")
    if any(hasattr(x, "addressable_shards") for x in data):
        import jax.numpy as jnp

        return jnp.concatenate(data, axis=dim)
    return np.concatenate([np.asarray(x) for x in data], axis=dim)


def convert_to_fp32(tensor):
    """Upcast floating leaves to fp32 (reference: operations.py:777)."""
    import jax.numpy as jnp

    def upcast(x):
        if is_array_like(x) and jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
            return x.astype(jnp.float32)
        return x

    return recursively_apply(upcast, tensor)


class ConvertOutputsToFp32:
    """Callable wrapper casting a function's float outputs to fp32
    (reference: operations.py:814 ``convert_outputs_to_fp32``)."""

    def __init__(self, model_forward):
        self.model_forward = model_forward
        functools.update_wrapper(self, model_forward)

    def __call__(self, *args, **kwargs):
        return convert_to_fp32(self.model_forward(*args, **kwargs))


def convert_outputs_to_fp32(model_forward):
    return ConvertOutputsToFp32(model_forward)


# ---------------------------------------------------------------------------
# Host-level collectives
# ---------------------------------------------------------------------------


def _num_processes() -> int:
    return _jax().process_count()


def _verify_operation(func):
    """Debug-mode shape pre-verification before a cross-process collective
    (reference: operations.py:363-395)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        from ..state import PartialState

        state = PartialState._shared_state
        if state.get("_initialized") and state.get("debug") and _num_processes() > 1:
            data = args[0] if args else kwargs.get("tensor", kwargs.get("object_list"))
            skeleton = repr(get_data_structure(data))
            all_skeletons = gather_object([skeleton])
            if len(set(all_skeletons)) != 1:
                report = "\n".join(f"  process {i}: {s}" for i, s in enumerate(all_skeletons))
                raise DistributedOperationException(
                    f"Mismatched inputs to `{func.__name__}` across processes:\n{report}"
                )
        return func(*args, **kwargs)

    return wrapper


@_verify_operation
def gather(tensor):
    """Materialise the full (cross-process) value on host as numpy
    (reference: operations.py:418 — per-rank tensors -> concatenated).

    * global ``jax.Array`` (even partially addressable): full array via
      allgather of shards over DCN when needed.
    * host numpy per process: concatenation across processes along dim 0.
    """
    jax = _jax()

    def gather_one(x):
        if hasattr(x, "is_fully_addressable"):
            if x.is_fully_addressable:
                return np.asarray(jax.device_get(x))
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        if _num_processes() > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(np.asarray(x), tiled=True))
        return np.asarray(x)

    return recursively_apply(gather_one, tensor)


def gather_object(object_list: list):
    """Gather python objects from all processes into one list
    (reference: operations.py:506). Pickle -> padded uint8 -> allgather."""
    if _num_processes() == 1:
        return list(object_list)
    from jax.experimental import multihost_utils

    payload = pickle.dumps(object_list)
    length = np.array([len(payload)], dtype=np.int64)
    max_len = int(multihost_utils.process_allgather(length, tiled=False).max())
    buf = np.zeros((max_len,), dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    all_bufs = multihost_utils.process_allgather(buf, tiled=False)
    all_lens = multihost_utils.process_allgather(length, tiled=False).reshape(-1)
    out = []
    for i in range(all_bufs.shape[0]):
        out.extend(pickle.loads(all_bufs[i, : int(all_lens[i])].tobytes()))
    return out


@_verify_operation
def broadcast(tensor, from_process: int = 0):
    """Broadcast array leaves from one process to all
    (reference: operations.py:538)."""
    if _num_processes() == 1:
        return tensor
    from jax.experimental import multihost_utils

    def bcast(x):
        return np.asarray(multihost_utils.broadcast_one_to_all(np.asarray(x), is_source=_jax().process_index() == from_process))

    return recursively_apply(bcast, tensor)


def broadcast_object_list(object_list: list, from_process: int = 0):
    """Broadcast python objects (reference: operations.py:559). In-place
    semantics preserved: returns the (mutated) list."""
    if _num_processes() == 1:
        return object_list
    from jax.experimental import multihost_utils

    jax = _jax()
    is_src = jax.process_index() == from_process
    payload = pickle.dumps(list(object_list)) if is_src else b""
    length = multihost_utils.broadcast_one_to_all(np.array([len(payload)], np.int64), is_source=is_src)
    buf = np.zeros((int(length[0]),), dtype=np.uint8)
    if is_src:
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=is_src)
    result = pickle.loads(buf.tobytes())
    object_list[:] = result
    return object_list


_scatter_seq = 0


def scatter_object(objects, from_process: int = 0):
    """Deliver ``objects[p]`` to process ``p`` — a host-level scatter.

    The slice-before-send primitive behind dispatch-mode data loading
    (reference sends per-rank slices: data_loader.py:786-850): each
    receiver pulls ONLY its own payload over the coordinator's key-value
    store, so DCN traffic per step is O(global batch), not
    O(global batch x hosts) as a full-batch broadcast would be. Falls back
    to broadcast+index when no distributed client is attached (then the
    traffic argument is moot anyway: single coordinator-less launch).

    ``objects`` must be a list of length ``process_count`` on
    ``from_process``; it may be None elsewhere. Returns this process's item.
    """
    global _scatter_seq
    n = _num_processes()
    if n == 1:
        return objects[0]
    jax = _jax()
    pi = jax.process_index()
    client = None
    try:
        from jax._src import distributed

        client = distributed.global_state.client
    except Exception:
        client = None
    if client is None:
        payload = [objects] if pi == from_process else [None]
        broadcast_object_list(payload, from_process=from_process)
        return payload[0][pi]

    import base64

    # the coordinator KV store is a control-plane channel with a gRPC
    # message-size ceiling — large payloads are split into chunks keyed
    # chunk-by-chunk (receivers reassemble). Dispatch mode is a
    # convenience path (dataset reachable from one host), not the
    # high-throughput ingest path; shard-mode loaders read host-locally.
    chunk_bytes = 1 << 20
    tag = _scatter_seq  # every process calls in lockstep -> same tag
    _scatter_seq += 1
    if pi == from_process:
        if objects is None or len(objects) != n:
            raise ValueError(f"scatter_object needs a list of {n} payloads on the source process")
        for p in range(n):
            if p != from_process:
                encoded = base64.b64encode(pickle.dumps(objects[p])).decode("ascii")
                chunks = [encoded[i : i + chunk_bytes] for i in range(0, len(encoded), chunk_bytes)] or [""]
                client.key_value_set(f"accelerate_scatter/{tag}/{p}/n", str(len(chunks)))
                for ci, chunk in enumerate(chunks):
                    client.key_value_set(f"accelerate_scatter/{tag}/{p}/{ci}", chunk)
        return objects[from_process]
    n_chunks = int(client.blocking_key_value_get(f"accelerate_scatter/{tag}/{pi}/n", 300_000))
    parts = []
    for ci in range(n_chunks):
        parts.append(client.blocking_key_value_get(f"accelerate_scatter/{tag}/{pi}/{ci}", 300_000))
    for key in [f"accelerate_scatter/{tag}/{pi}/n"] + [f"accelerate_scatter/{tag}/{pi}/{ci}" for ci in range(n_chunks)]:
        try:
            client.key_value_delete(key)
        except Exception:
            pass
    return pickle.loads(base64.b64decode("".join(parts)))


@_verify_operation
def reduce(tensor, reduction: str = "mean", scale: float = 1.0):
    """Elementwise reduce across processes (reference: operations.py:723)."""
    def red(x):
        x = np.asarray(x if not hasattr(x, "addressable_shards") else _jax().device_get(x))
        if _num_processes() > 1:
            from jax.experimental import multihost_utils

            stacked = multihost_utils.process_allgather(x, tiled=False)
            x = stacked.sum(axis=0)
            if reduction == "mean":
                x = x / stacked.shape[0]
        return x * scale

    return recursively_apply(red, tensor)


@_verify_operation
def pad_across_processes(tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad each process's arrays to the max size along ``dim`` so a gather
    is well-formed (reference: operations.py:627)."""
    def pad(x):
        x = np.asarray(x if not hasattr(x, "addressable_shards") else _jax().device_get(x))
        if dim >= x.ndim:
            return x
        size = np.array([x.shape[dim]], dtype=np.int64)
        if _num_processes() > 1:
            from jax.experimental import multihost_utils

            max_size = int(multihost_utils.process_allgather(size, tiled=False).max())
        else:
            max_size = int(size[0])
        if max_size == x.shape[dim]:
            return x
        pad_width = [(0, 0)] * x.ndim
        pad_width[dim] = (max_size - x.shape[dim], 0) if pad_first else (0, max_size - x.shape[dim])
        return np.pad(x, pad_width, constant_values=pad_index)

    return recursively_apply(pad, tensor)


def pad_input_tensors(tensor, batch_size: int, num_processes: int, dim: int = 0):
    """Pad batch so it divides evenly (reference: operations.py:694)."""
    def pad(x):
        x = np.asarray(x)
        remainder = x.shape[dim] % num_processes
        if remainder == 0:
            return x
        extra = num_processes - remainder
        take = [slice(None)] * x.ndim
        take[dim] = slice(0, extra)
        filler = x[tuple(take)]
        if filler.shape[dim] < extra:  # repeat last rows if batch < procs
            reps = [1] * x.ndim
            reps[dim] = int(np.ceil(extra / max(1, filler.shape[dim])))
            filler = np.tile(filler, reps)
            take[dim] = slice(0, extra)
            filler = filler[tuple(take)]
        return np.concatenate([x, filler], axis=dim)

    return recursively_apply(pad, tensor)


def initialize_tensors(data_structure):
    """Materialise zeros from a shape skeleton (reference: operations.py:226)."""
    jax = _jax()

    def init(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return np.zeros(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(init, data_structure, is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
