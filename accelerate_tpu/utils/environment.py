"""Environment parsing and manipulation helpers.

Plays the role of the reference's ``utils/environment.py``
(reference: src/accelerate/utils/environment.py:59-360): string->bool parsing,
flag parsing from env, and context managers to clear/patch the process
environment. CUDA/NUMA-specific helpers from the reference have no TPU
meaning and are intentionally absent.
"""

from __future__ import annotations

import functools
import os
from contextlib import contextmanager

_TRUE = {"1", "true", "yes", "on", "y", "t"}
_FALSE = {"0", "false", "no", "off", "n", "f", ""}


def str_to_bool(value: str) -> int:
    """Convert a string to 1/0 (reference: utils/environment.py:59)."""
    value = str(value).lower().strip()
    if value in _TRUE:
        return 1
    if value in _FALSE:
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def get_int_from_env(env_keys, default: int) -> int:
    """First set env var among ``env_keys`` parsed as int, else ``default``."""
    for key in env_keys:
        val = os.environ.get(key)
        if val is not None and val != "":
            return int(val)
    return default


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    """Parse a boolean flag from the environment (reference: utils/environment.py:83)."""
    value = os.environ.get(key)
    if value is None:
        return default
    return bool(str_to_bool(value))


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, default)


def are_libraries_initialized(*library_names: str) -> list[str]:
    """Return the subset of ``library_names`` already imported in this process."""
    import sys

    return [name for name in library_names if name in sys.modules]


@contextmanager
def clear_environment():
    """Temporarily empty ``os.environ`` (reference: utils/environment.py:291)."""
    saved = dict(os.environ)
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(saved)


@contextmanager
def patch_environment(**kwargs):
    """Temporarily set env vars; keys are upper-cased (reference: utils/environment.py:326)."""
    saved = {}
    missing = object()
    for key, value in kwargs.items():
        key = key.upper()
        saved[key] = os.environ.get(key, missing)
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key, value in saved.items():
            if value is missing:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


def purge_accelerate_environment(func):
    """Decorator: run ``func`` with all ``ACCELERATE_*`` env vars removed
    (reference: utils/environment.py:362). Used by the test harness so state
    leakage between tests cannot occur through the env-var protocol."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        saved = {k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_")}
        for k in saved:
            del os.environ[k]
        try:
            return func(*args, **kwargs)
        finally:
            os.environ.update(saved)

    return wrapper


def force_host_platform(n_devices: int = 8) -> None:
    """Force the JAX CPU (host) platform with ``n_devices`` virtual devices.

    The single authoritative bootstrap for every fake-mesh entry point
    (tests/conftest.py, ``__graft_entry__.dryrun_multichip``, bench smoke
    mode). Env vars alone are NOT enough: the axon TPU plugin registers at
    interpreter start and wins over ``JAX_PLATFORMS``; only the
    ``jax.config`` override reliably forces CPU. Must run before the first
    backend use in this process; if a backend was already initialised it is
    dropped so the CPU platform (re-)initialises with the requested count.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    opt = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
    else:
        flags = f"{flags} {opt}"
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:
        pass


def get_free_port() -> int:
    """An OS-assigned free TCP port (reference: utils/other.py:474
    ``get_free_port``) — used by the launcher so concurrent local process
    groups don't collide on the default coordinator port."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
