"""Seeding and cross-process RNG synchronisation.

Reference analogue: src/accelerate/utils/random.py (set_seed :39,
synchronize_rng_states :154 — broadcasts torch RNG state from rank 0).

JAX RNG is explicit (keys, not global state), which makes the reference's
hardest problem — "same shuffle on every rank" — trivial: every process
derives the same key from the same seed, and per-step/per-host streams are
``jax.random.fold_in`` folds, never mutation. What still needs syncing is
the *host-side* RNG (numpy/python) used by dataloader shuffling when no
seed was given; ``synchronize_rng_states`` broadcasts those from process 0.
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

import numpy as np

from .dataclasses import RNGType
from .operations import broadcast_object_list


_GLOBAL_SEED: Optional[int] = None


def set_seed(seed: int, device_specific: bool = False, deterministic: bool = False) -> None:
    """Seed python/numpy and record the seed for JAX key derivation
    (reference: utils/random.py:39). ``device_specific`` folds in the
    process index so hosts draw distinct-but-reproducible streams."""
    global _GLOBAL_SEED
    if device_specific:
        import jax

        seed += jax.process_index()
    _GLOBAL_SEED = seed
    random.seed(seed)
    np.random.seed(seed % (2**32))


def get_seed() -> Optional[int]:
    return _GLOBAL_SEED


def restore_seed_for_keys(seed: Optional[int]) -> None:
    """Restore the recorded seed for JAX key derivation WITHOUT reseeding
    the host RNGs. Checkpoint load uses this: python/numpy states are
    restored bit-exactly from the pickle, so a ``set_seed`` here would
    clobber their positions back to the start of the stream."""
    global _GLOBAL_SEED
    if seed is not None:
        _GLOBAL_SEED = seed


def root_key():
    """The process-identical root PRNG key (requires prior ``set_seed``)."""
    import jax

    if _GLOBAL_SEED is None:
        set_seed(0)
    return jax.random.key(_GLOBAL_SEED)


def key_for_step(step: int, *folds: int):
    """Derive a per-step (and optionally per-axis-index) key by folding —
    the idiomatic replacement for the reference's RNG-state broadcast."""
    import jax

    k = jax.random.fold_in(root_key(), step)
    for f in folds:
        k = jax.random.fold_in(k, f)
    return k


def synchronize_rng_state(rng_type: Optional[RNGType] = None, generator=None) -> None:
    """Broadcast one host RNG state from process 0 (reference:
    utils/random.py:106)."""
    import jax

    if jax.process_count() == 1:
        return
    if rng_type == RNGType.NUMPY:
        state = [np.random.get_state()]
        broadcast_object_list(state, from_process=0)
        np.random.set_state(state[0])
    elif rng_type == RNGType.PYTHON:
        state = [random.getstate()]
        broadcast_object_list(state, from_process=0)
        random.setstate(state[0])
    elif rng_type == RNGType.JAX:
        # JAX keys are derived from the shared seed; broadcast the seed.
        global _GLOBAL_SEED
        state = [_GLOBAL_SEED]
        broadcast_object_list(state, from_process=0)
        if state[0] is not None:
            _GLOBAL_SEED = state[0]
    elif generator is not None:
        state = [generator.bit_generator.state]
        broadcast_object_list(state, from_process=0)
        generator.bit_generator.state = state[0]


def synchronize_rng_states(rng_types: Iterable[str], generator=None) -> None:
    """(reference: utils/random.py:154)."""
    for rng_type in rng_types:
        synchronize_rng_state(RNGType(rng_type), generator=generator)
