"""Config dataclasses, enums, and kwargs handlers.

Plays the role of the reference's ``utils/dataclasses.py`` (2833 LoC —
reference: src/accelerate/utils/dataclasses.py). The biggest structural
difference: the reference needs a 14-value ``DistributedType`` plus five
strategy plugins because each strategy is a separate code path; here a
strategy is a :class:`~accelerate_tpu.parallel.mesh.MeshConfig` layout, so
``DistributedType`` collapses to a descriptive label derived from the mesh.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import os
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Optional

from .environment import parse_flag_from_env
from ..parallel.mesh import MeshConfig


class BaseEnum(str, enum.Enum):
    def __str__(self) -> str:  # so f-strings print the value
        return self.value

    @classmethod
    def list(cls) -> list[str]:
        return [e.value for e in cls]


class DistributedType(BaseEnum):
    """Descriptive label for the active parallelism layout
    (reference enum with 14 backend-specific values:
    src/accelerate/utils/dataclasses.py:555-588)."""

    NO = "NO"
    DATA_PARALLEL = "DATA_PARALLEL"
    FSDP = "FSDP"
    TENSOR_PARALLEL = "TENSOR_PARALLEL"
    SEQUENCE_PARALLEL = "SEQUENCE_PARALLEL"
    PIPELINE_PARALLEL = "PIPELINE_PARALLEL"
    EXPERT_PARALLEL = "EXPERT_PARALLEL"
    HYBRID = "HYBRID"

    @classmethod
    def from_mesh_sizes(cls, sizes: dict[str, int]) -> "DistributedType":
        active = [a for a, n in sizes.items() if n > 1]
        if not active:
            return cls.NO
        if len(active) > 1:
            return cls.HYBRID
        return {
            "data": cls.DATA_PARALLEL,
            "fsdp": cls.FSDP,
            "tensor": cls.TENSOR_PARALLEL,
            "seq": cls.SEQUENCE_PARALLEL,
            "pipe": cls.PIPELINE_PARALLEL,
            "expert": cls.EXPERT_PARALLEL,
        }[active[0]]


class PrecisionType(BaseEnum):
    """(reference: utils/dataclasses.py:724). fp16 exists for API parity but
    bf16 is the TPU-native mixed-precision mode — no loss scaling needed."""

    NO = "no"
    BF16 = "bf16"
    FP16 = "fp16"
    FP8 = "fp8"


class RNGType(BaseEnum):
    JAX = "jax"
    NUMPY = "numpy"
    PYTHON = "python"


class LoggerType(BaseEnum):
    ALL = "all"
    TENSORBOARD = "tensorboard"
    WANDB = "wandb"
    MLFLOW = "mlflow"
    AIM = "aim"
    COMETML = "comet_ml"
    CLEARML = "clearml"
    DVCLIVE = "dvclive"
    SWANLAB = "swanlab"
    TRACKIO = "trackio"
    JSONL = "jsonl"


# ---------------------------------------------------------------------------
# Kwargs handlers (reference: utils/dataclasses.py:109-552)
# ---------------------------------------------------------------------------


class KwargsHandler:
    """Base for kwargs containers passed to ``Accelerator(kwargs_handlers=[...])``."""

    def to_dict(self) -> dict:
        return copy.deepcopy(dataclasses.asdict(self))

    def to_kwargs(self) -> dict:
        """Only the fields that differ from the defaults."""
        default = self.__class__()
        this = dataclasses.asdict(self)
        return {k: v for k, v in this.items() if getattr(default, k) != v}


@dataclass
class AutocastKwargs(KwargsHandler):
    """Compute-dtype policy tweaks (reference: utils/dataclasses.py:109).
    On TPU "autocast" is a dtype policy applied when building the jitted
    step, not a runtime context."""

    enabled: bool = True
    # dtypes kept out of low precision even under mixed precision
    keep_fp32_patterns: tuple = ("layernorm", "layer_norm", "ln_", "norm", "embedding_norm")


@dataclass
class DistributedInitKwargs(KwargsHandler):
    """Multi-host rendezvous options — the ``jax.distributed.initialize``
    analogue of ``InitProcessGroupKwargs`` (reference:
    utils/dataclasses.py:260)."""

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    local_device_ids: Optional[list] = None
    timeout: timedelta = timedelta(minutes=10)


@dataclass
class GradScalerKwargs(KwargsHandler):
    """Dynamic loss-scaling knobs for fp16 (reference:
    utils/dataclasses.py:228). bf16 runs need none of this."""

    init_scale: float = 2.0**15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    enabled: bool = True


@dataclass
class Fp8RecipeKwargs(KwargsHandler):
    """TE-style fp8 recipe knobs (reference: ``TERecipeKwargs``,
    utils/dataclasses.py:317 + utils/transformer_engine.py:26-163).

    ``delayed_scaling=True`` keeps an amax history per tensor (a flax
    ``fp8`` collection threaded through the train step) and derives the
    quantization scale from ``max(history) * 2**margin`` — the TE
    "DelayedScaling" recipe; ``False`` recomputes per-tensor amax every
    call (the dynamic recipe, no state)."""

    delayed_scaling: bool = True
    amax_history_len: int = 16
    amax_compute_algo: str = "max"  # "max" | "most_recent"
    margin: int = 0

    def __post_init__(self):
        if self.amax_compute_algo not in ("max", "most_recent"):
            raise ValueError(f"amax_compute_algo must be max|most_recent, got {self.amax_compute_algo!r}")
        if self.amax_history_len < 1:
            raise ValueError(f"amax_history_len must be >= 1, got {self.amax_history_len}")


@dataclass
class ProfileKwargs(KwargsHandler):
    """``jax.profiler`` options (reference torch.profiler kwargs:
    utils/dataclasses.py:439-552). Traces are TensorBoard/Perfetto-viewable.

    The tracer levels map to XLA profiler options
    (``host_tracer_level`` 0-3, ``python_tracer_level`` 0/1,
    ``device_tracer_level`` 0/1); ``Accelerator.profile`` passes them
    through when the installed jax supports profiler options and warns
    ONCE per process about any option it has to drop — a silently-ignored
    knob is worse than no knob."""

    output_trace_dir: Optional[str] = None
    create_perfetto_link: bool = False
    create_perfetto_trace: bool = True
    host_tracer_level: int = 2
    python_tracer_level: int = 0
    device_tracer_level: int = 1
    on_trace_ready: Optional[Callable] = None


@dataclass
class TelemetryKwargs(KwargsHandler):
    """Runtime-telemetry knobs consumed by ``Accelerator.telemetry``
    (see :mod:`accelerate_tpu.telemetry`). No reference analogue — the
    reference has no runtime observability layer.

    ``output_path=None`` writes to ``{logging_dir}/telemetry.jsonl``
    (``runs/telemetry.jsonl`` when no logging/project dir is set);
    ``fence=False`` drops the per-step ``block_until_ready`` (the
    data-wait/dispatch/execute split then degrades but overhead reaches
    zero); ``forward_to_trackers_every=N`` pushes a rolling summary
    through ``Accelerator.log`` every N steps (0 disables);
    ``nonfinite_every=N`` opts in to the
    :class:`~accelerate_tpu.telemetry.NonFiniteWatchdog` — every N steps
    the fast-path train step probes loss / grad-norm finiteness and the
    fp16 loss-scale trajectory (a probe is a host sync, so 0 = off is
    the default; the static counterpart is
    ``Accelerator.numerics_check``'s TPU602 proof)."""

    enabled: bool = True
    output_path: Optional[str] = None
    # 2, not 1: the train step's second call may legitimately compile a
    # second program variant (sharding propagation re-lays-out the carried
    # gradient buffer) — see StepTelemetry's docstring
    warmup_steps: int = 2
    fence: bool = True
    recompile_watchdog: bool = True
    hbm_sample_every: int = 10
    forward_to_trackers_every: int = 10
    nonfinite_every: int = 0
    main_process_only: bool = True
    # serving-side request tracing (telemetry.trace): trace_requests=True
    # turns :meth:`trace_config` into a TraceConfig suitable for
    # ``FleetRouter(trace=...)`` — per-request spans, per-replica crash
    # flight recorders, and the critical-path drift cross-checks
    trace_requests: bool = False
    trace_max_traces: int = 4096
    trace_drift_check: bool = True
    flight_recorder: bool = True
    flight_capacity: int = 256
    flight_dump_dir: Optional[str] = None

    def __post_init__(self):
        if self.warmup_steps < 0:
            raise ValueError(f"warmup_steps must be >= 0, got {self.warmup_steps}")
        if self.hbm_sample_every < 0 or self.forward_to_trackers_every < 0:
            raise ValueError("hbm_sample_every / forward_to_trackers_every must be >= 0")
        if self.nonfinite_every < 0:
            raise ValueError(f"nonfinite_every must be >= 0, got {self.nonfinite_every}")
        if self.trace_max_traces < 1:
            raise ValueError(f"trace_max_traces must be >= 1, got {self.trace_max_traces}")
        if self.flight_capacity < 8:
            raise ValueError(f"flight_capacity must be >= 8, got {self.flight_capacity}")

    def trace_config(self):
        """The serving-trace half of these knobs as a
        :class:`~accelerate_tpu.telemetry.TraceConfig` (None when
        ``trace_requests`` is off) — pass as ``FleetRouter(trace=...)``."""
        if not self.trace_requests:
            return None
        from ..telemetry.trace import TraceConfig

        return TraceConfig(
            max_traces=self.trace_max_traces,
            drift_check=self.trace_drift_check,
            flight_recorder=self.flight_recorder,
            flight_capacity=self.flight_capacity,
            flight_dump_dir=self.flight_dump_dir,
        )


@dataclass
class ServingSchedulerKwargs(KwargsHandler):
    """Continuous-batching scheduler knobs for
    :class:`~accelerate_tpu.serving.ServingEngine` — the kwargs-handler
    mirror of :class:`~accelerate_tpu.scheduling.SchedulerConfig`, so
    serving deployments configure the scheduler the same way training
    configures telemetry/compile management. Pass it as
    ``ServingEngine(..., scheduler=ServingSchedulerKwargs(...))``.

    ``token_budget``: model-compute tokens per engine tick — active
    decodes claim ``n_decoding x tick_block`` first, the remainder runs
    prefill *chunks*, so long prompts stream in without stalling running
    decodes (``None`` = unlimited: prefills complete at admission).
    ``max_queue_depth`` / ``max_queue_wait_s``: SLO shed thresholds for
    priorities >= ``shed_priority_floor`` (``shed_action`` picks
    reject-with-:class:`~accelerate_tpu.scheduling.ShedError` or
    demote-to-``deprioritize_to``). ``enable_preemption``: evict the
    youngest decode with priority >= ``preempt_priority_floor`` when a
    strictly more important request cannot admit; it requeues and
    resumes token-exactly by recompute. ``speculative_priorities``:
    with a draft model, restrict the speculative tick to these classes.
    ``mode="fifo"`` pins the legacy strict-FIFO behavior (benchmark
    baseline)."""

    mode: str = "continuous"
    token_budget: Optional[int] = None
    max_queue_depth: Optional[int] = None
    max_queue_wait_s: Optional[float] = None
    shed_priority_floor: int = 1
    shed_action: str = "reject"
    deprioritize_to: int = 99
    enable_preemption: bool = False
    preempt_priority_floor: int = 1
    speculative_priorities: Optional[tuple] = None

    def to_scheduler_config(self):
        """The :class:`~accelerate_tpu.scheduling.SchedulerConfig` the
        engine consumes (validation happens there)."""
        from ..scheduling import SchedulerConfig

        return SchedulerConfig(**dataclasses.asdict(self))


@dataclass
class CompileKwargs(KwargsHandler):
    """Compile-management knobs consumed by ``Accelerator.program_cache``
    (see :mod:`accelerate_tpu.aot` and ``docs/usage_guides/compilation.md``).
    No reference analogue — the reference delegates compilation to torch.

    Passing this handler *activates* the subsystem: jax's persistent XLA
    compilation cache is pointed at the resolved cache dir, an
    :class:`~accelerate_tpu.aot.ExecutableStore` of serialized executables
    is opened next to it, and ``build_train_step`` routes its programs
    through the shared :class:`~accelerate_tpu.aot.ProgramCache` so a
    restarted process (new serving replica, preemption-resumed trainer)
    deserializes instead of recompiling. Setting
    ``ACCELERATE_COMPILE_CACHE_DIR`` activates the same default
    configuration without code changes.

    ``cache_dir=None`` resolves via ``ACCELERATE_COMPILE_CACHE_DIR``,
    then ``{ProjectConfiguration.project_dir}/compile_cache`` (see
    :func:`accelerate_tpu.aot.resolve_cache_dir`); with no dir at all the
    cache still deduplicates and emits telemetry, memory-only."""

    cache_dir: Optional[str] = None
    #: also wire jax's own persistent compilation cache (at
    #: ``{cache_dir}/xla``) — saves the XLA optimization pass even for
    #: programs the executable store doesn't cover
    persistent_xla_cache: bool = True
    #: keep serialized ``lower().compile()`` executables on disk so a new
    #: process warm-starts with zero XLA compiles
    executable_store: bool = True
    #: only persist XLA-cache entries that took at least this long to
    #: compile (jax's own default; 0 keeps everything, which floods the
    #: dir with micro-program entries)
    min_compile_time_secs: float = 1.0
    #: route ``build_train_step``'s program dispatch through the
    #: ProgramCache (the AOT warm-start path); False keeps plain jax.jit
    aot_train_step: bool = True

    def __post_init__(self):
        if self.min_compile_time_secs < 0:
            raise ValueError(f"min_compile_time_secs must be >= 0, got {self.min_compile_time_secs}")


@dataclass
class FaultToleranceKwargs(KwargsHandler):
    """Fault-tolerance knobs (see :mod:`accelerate_tpu.ft` and
    ``docs/usage_guides/fault_tolerance.md``). No reference analogue —
    the reference has no preemption/atomic-commit layer.

    Passing this handler to ``Accelerator(kwargs_handlers=[...])`` also
    *activates* the opt-in behaviors: the SIGTERM/SIGINT preemption
    handler (``handle_preemption``) and retried tracker network calls
    (``tracker_retries``). The atomic commit protocol itself is always
    on — correctness is not opt-in — these knobs only tune its retries
    and GC."""

    #: install a PreemptionHandler so SIGTERM/SIGINT surface as
    #: ``Accelerator.should_checkpoint`` / ``should_stop``
    handle_preemption: bool = True
    preemption_signals: tuple = ("SIGTERM", "SIGINT")
    #: multi-host: max-reduce the local preempt flag across every process
    #: each time ``should_checkpoint``/``should_stop`` is read, so a
    #: SIGTERM delivered to a subset of hosts flips the flag on ALL ranks
    #: in the same step (one scalar all-gather per check; single-process
    #: runs never pay it)
    agree_preemption: bool = True
    #: jittered-exponential-backoff attempts for checkpoint filesystem IO
    io_retries: int = 3
    retry_base_delay: float = 0.1
    retry_max_delay: float = 5.0
    #: retried attempts for tracker ``log`` network calls (giving up logs a
    #: warning instead of killing the run); 1 disables
    tracker_retries: int = 3
    #: sweep stale ``checkpoint_*.tmp`` leftovers at the start of each
    #: automatic-naming save (recovering any fully committed one)
    gc_tmp_on_save: bool = True
    #: deep-verify manifests (sizes + crc32) during auto-resume discovery;
    #: False trusts manifest presence alone (faster on huge checkpoints)
    verify_on_resume: bool = True

    def __post_init__(self):
        if self.io_retries < 1 or self.tracker_retries < 1:
            raise ValueError("io_retries / tracker_retries must be >= 1")
        if self.retry_base_delay < 0 or self.retry_max_delay < self.retry_base_delay:
            raise ValueError("need 0 <= retry_base_delay <= retry_max_delay")


# ---------------------------------------------------------------------------
# Plugins
# ---------------------------------------------------------------------------


@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """(reference: utils/dataclasses.py:931). ``sync_with_dataloader`` forces
    a sync on the last batch of each dataloader pass."""

    num_steps: int = 1
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False

    def __post_init__(self):
        if self.num_steps < 1:
            raise ValueError(f"gradient accumulation num_steps must be >= 1, got {self.num_steps}")


@dataclass
class DataLoaderConfiguration(KwargsHandler):
    """(reference: utils/dataclasses.py:773)."""

    split_batches: bool = False
    dispatch_batches: Optional[bool] = None
    even_batches: bool = True
    use_seedable_sampler: bool = True
    prefetch_size: int = 2
    non_blocking: bool = True  # kept for API parity; device_put is async
    #: pad ragged batch dims to a learned bucket set
    #: (:class:`~accelerate_tpu.aot.ShapeBucketer`) so a variable tail
    #: batch (or a variable-size stream) compiles at most len(buckets)
    #: programs instead of one per distinct size — the auto-bucketing
    #: loop-closer for the PR-3 recompile watchdog. Padded rows wrap
    #: around from the batch start (``even_batches`` tail semantics) and
    #: are truncated by the existing ``remainder`` bookkeeping.
    auto_bucketing: bool = False


@dataclass
class ProjectConfiguration(KwargsHandler):
    """Checkpoint/log directory layout (reference: utils/dataclasses.py:868)."""

    project_dir: Optional[str] = None
    logging_dir: Optional[str] = None
    automatic_checkpoint_naming: bool = False
    total_limit: Optional[int] = None
    iteration: int = 0
    save_on_each_node: bool = False
    #: subdirectory of ``project_dir`` holding the ``checkpoint_N`` family
    #: (save, auto-resume, and ``Accelerator.checkpoint_manager`` all use it)
    checkpoints_dir_name: str = "checkpoints"
    #: subdirectory of ``project_dir`` for the compile cache (persistent
    #: XLA cache + serialized-executable store) when a ``CompileKwargs``
    #: handler is active and neither ``CompileKwargs.cache_dir`` nor
    #: ``ACCELERATE_COMPILE_CACHE_DIR`` names one explicitly
    compile_cache_dir_name: str = "compile_cache"

    def set_directories(self, project_dir: Optional[str] = None):
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self):
        if self.logging_dir is None:
            self.logging_dir = self.project_dir


@dataclass
class MixedPrecisionPolicy(KwargsHandler):
    """The dtype policy used to build the jitted step: params stay in
    ``param_dtype`` (fp32 master copy), matmuls run in ``compute_dtype``,
    outputs/loss come back in fp32 — the structural equivalent of the
    reference's autocast-wrap + ``convert_outputs_to_fp32``
    (reference: accelerator.py:1590-1601, operations.py:814)."""

    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    output_dtype: str = "float32"
    # Attention softmax math dtype. None (default) keeps the f32 logits /
    # softmax chain — the numerically conservative choice. "bfloat16" skips
    # the f32 materialisation of the [B, H, S, S] logits: measured 1.10x on
    # the BERT-base v5e step (170.4 -> 154.8 ms, loss trajectory within
    # 1.5e-4 after 20 steps; benchmarks/README.md "step breakdown") — the
    # step is HBM-bound and the f32 score tensors are its biggest
    # avoidable traffic. Opt in when your convergence gates pass with it.
    softmax_dtype: Optional[str] = None
    # fp8 mode: the blanket cast stays bf16 (casting raw params/activations
    # to e4m3 without per-tensor scaling destroys training); hot matmuls use
    # the scaled e4m3 path (utils.quantization.fp8_dot — the TE-recipe
    # equivalent, reference: utils/transformer_engine.py:26-163)
    fp8: bool = False

    @classmethod
    def from_mixed_precision(cls, mixed_precision: str) -> "MixedPrecisionPolicy":
        mp = PrecisionType(mixed_precision or "no")
        if mp == PrecisionType.NO:
            return cls(compute_dtype="float32")
        if mp == PrecisionType.BF16:
            return cls(compute_dtype="bfloat16")
        if mp == PrecisionType.FP16:
            return cls(compute_dtype="float16")
        if mp == PrecisionType.FP8:
            return cls(compute_dtype="bfloat16", fp8=True)
        raise ValueError(mixed_precision)


@dataclass
class ParallelismPlugin(KwargsHandler):
    """The one strategy plugin: a mesh layout + sharding rules + remat policy.

    Subsumes the reference's ``FullyShardedDataParallelPlugin`` (~580 lines,
    utils/dataclasses.py:1489), ``TorchTensorParallelPlugin`` (:2070),
    ``DeepSpeedPlugin`` (:1059) and ``MegatronLMPlugin`` (:2112)."""

    mesh_config: MeshConfig = field(default_factory=MeshConfig)
    # explicit (regex, PartitionSpec) rules; None -> auto (model-provided
    # rules if available, else fsdp auto-rules when fsdp axis > 1)
    sharding_rules: Optional[Any] = None
    # ZeRO-1/2: shard optimizer state over the data axis even when params
    # are replicated ("cross-replica weight-update sharding"). This is the
    # PASSIVE layout mode: the update itself stays replicated and GSPMD
    # moves shards around it. Works with any optax transformation.
    shard_optimizer_state: bool = False
    # ZeRO-1, the EXPLICIT wire mode (docs/usage_guides/zero_redundancy.md):
    # reduce-scatter grads over the data axes -> each replica updates only
    # its 1/n flat segment of params + optimizer state (state *born*
    # sharded, so per-device optimizer HBM divides by n from step 0) ->
    # all-gather the updates. Composes with grad_compression
    # ("bf16"|"int8"|"fp8"): both wire legs carry quantized payloads with
    # per-rank error feedback. Requires an elementwise optax
    # transformation (sgd/adam/adamw/...; use shard_optimizer_state for
    # factored/coupled ones) and the fast path (build_train_step).
    zero_stage: int = 0
    # ZeRO-offload analogue (reference: DeepSpeedPlugin
    # offload_optimizer_device / FSDP cpu_offload,
    # utils/dataclasses.py:1100-1180): optimizer moments live on
    # ``pinned_host`` memory-kind shardings and stream through HBM inside
    # the jitted step — HBM high-water mark drops by the state bytes
    # (2x fp32 params for Adam) at the cost of PCIe/host traffic per
    # sync boundary. Composes with shard_optimizer_state (the host copy
    # keeps the ZeRO layout).
    offload_optimizer: bool = False
    # activation rematerialisation policy name (see accelerator.build_train_step)
    remat_policy: Optional[str] = None
    donate_state: bool = True
    # compress the data-parallel gradient reduction ("bf16" | "int8" |
    # "powersgd[:rank]") — the reference's DDP comm hooks incl. PowerSGD
    # (utils/dataclasses.py:130-226), for multi-host data axes where DCN
    # bytes are the bottleneck
    grad_compression: Optional[str] = None

    @classmethod
    def from_env(cls) -> "ParallelismPlugin":
        return cls(
            mesh_config=MeshConfig.from_env(),
            shard_optimizer_state=parse_flag_from_env("ACCELERATE_SHARD_OPTIMIZER_STATE"),
            zero_stage=int(os.environ.get("ACCELERATE_ZERO_STAGE", "0") or 0),
            offload_optimizer=parse_flag_from_env("ACCELERATE_OFFLOAD_OPTIMIZER"),
            remat_policy=os.environ.get("ACCELERATE_REMAT_POLICY") or None,
            grad_compression=os.environ.get("ACCELERATE_GRAD_COMPRESSION") or None,
        )

    def __post_init__(self):
        if self.grad_compression is not None and self.grad_compression not in ("bf16", "int8", "fp8"):
            from ..parallel.compression import powersgd_rank

            if powersgd_rank(self.grad_compression) is None:
                raise ValueError(
                    f"grad_compression must be bf16|int8|fp8|powersgd[:rank], got {self.grad_compression!r}"
                )
        if self.zero_stage not in (0, 1):
            raise ValueError(f"zero_stage must be 0 or 1, got {self.zero_stage!r}")
        if self.zero_stage:
            from ..parallel.compression import powersgd_rank

            if powersgd_rank(self.grad_compression) is not None:
                raise ValueError(
                    "zero_stage=1 does not compose with grad_compression='powersgd' "
                    "(low-rank factors are psum-shaped, not reduce-scatterable); "
                    "use bf16|int8|fp8"
                )
            if self.offload_optimizer:
                raise ValueError(
                    "zero_stage=1 already shards the optimizer state 1/n per device; "
                    "it does not compose with offload_optimizer (pick one)"
                )
            if self.shard_optimizer_state:
                raise ValueError(
                    "pass either zero_stage=1 (explicit reduce-scatter/all-gather wire) "
                    "or shard_optimizer_state=True (passive GSPMD layout), not both"
                )


def add_model_config_to_megatron_parser(*a, **k):  # pragma: no cover
    raise NotImplementedError("Megatron-LM integration does not exist on TPU; use ParallelismPlugin mesh axes")
