"""Version comparison helpers (reference: src/accelerate/utils/versions.py)."""

from __future__ import annotations

import importlib.metadata
import operator

_OPS = {
    "<": operator.lt,
    "<=": operator.le,
    "==": operator.eq,
    "!=": operator.ne,
    ">=": operator.ge,
    ">": operator.gt,
}


def _parse(version: str) -> tuple:
    parts = []
    for piece in version.split("+")[0].split("."):
        digits = "".join(ch for ch in piece if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def compare_versions(library_or_version, op: str, requirement_version: str) -> bool:
    """Compare an installed library's version (or a literal version string)
    against ``requirement_version`` with operator ``op``."""
    if op not in _OPS:
        raise ValueError(f"operator must be one of {sorted(_OPS)}, got {op!r}")
    if not isinstance(library_or_version, str) or any(c.isalpha() for c in library_or_version.split(".")[0]):
        # looks like a library name
        library_or_version = importlib.metadata.version(str(library_or_version))
    a, b = _parse(library_or_version), _parse(requirement_version)
    # zero-pad to equal length so (0, 12) == (0, 12, 0)
    n = max(len(a), len(b))
    a += (0,) * (n - len(a))
    b += (0,) * (n - len(b))
    return _OPS[op](a, b)


def is_jax_version(op: str, version: str) -> bool:
    import jax

    return compare_versions(jax.__version__, op, version)
