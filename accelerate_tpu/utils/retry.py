"""Jittered exponential-backoff retry for flaky IO.

Checkpoint writes hit GCS/NFS (transient 5xx, stale handles) and tracker
calls hit the network; both should survive a blip without killing a
multi-hour run. ``retry`` is deliberately narrow by default — it retries
``OSError`` only, so programming errors (and the fault-injection
harness's ``SimulatedCrash``) propagate immediately.

::

    @retry(attempts=4, base_delay=0.2)
    def _write(path, data): ...

    retry_call(tracker.log, values, attempts=3, on_retry=log_event)

``on_retry(attempt, delay, exc)`` fires before each sleep — the
checkpoint path emits ``ckpt_retry`` telemetry events through it, so a
run report shows every transient failure that was absorbed.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

#: exceptions retried by default: filesystem / network IO surfaces as
#: OSError (IOError is an alias; gcsfs/fsspec raise OSError subclasses)
DEFAULT_EXCEPTIONS: Tuple[Type[BaseException], ...] = (OSError,)


def backoff_delays(attempts: int, base_delay: float, max_delay: float, jitter: float, rng=random.random):
    """The sleep schedule between attempts: ``base * 2**i`` capped at
    ``max_delay``, each scaled by ``1 + jitter*U[0,1)`` so a pod of hosts
    retrying the same dead filer doesn't thundering-herd in lockstep."""
    for i in range(max(0, attempts - 1)):
        yield min(max_delay, base_delay * (2**i)) * (1.0 + jitter * rng())


def retry_call(
    fn: Callable,
    *args,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    exceptions: Tuple[Type[BaseException], ...] = DEFAULT_EXCEPTIONS,
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    on_giveup: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying on ``exceptions`` with
    jittered exponential backoff. Re-raises the last exception after
    ``attempts`` tries (after ``on_giveup``, if given)."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delays = backoff_delays(attempts, base_delay, max_delay, jitter)
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args, **kwargs)
        except exceptions as e:
            if attempt == attempts:
                if on_giveup is not None:
                    on_giveup(attempt, e)
                raise
            delay = next(delays)
            if on_retry is not None:
                on_retry(attempt, delay, e)
            sleep(delay)


def retry(
    fn: Optional[Callable] = None,
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    exceptions: Tuple[Type[BaseException], ...] = DEFAULT_EXCEPTIONS,
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    on_giveup: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Decorator form of :func:`retry_call` (bare ``@retry`` or
    ``@retry(attempts=5, ...)``)."""

    def decorate(f: Callable) -> Callable:
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return retry_call(
                f,
                *args,
                attempts=attempts,
                base_delay=base_delay,
                max_delay=max_delay,
                jitter=jitter,
                exceptions=exceptions,
                on_retry=on_retry,
                on_giveup=on_giveup,
                sleep=sleep,
                **kwargs,
            )

        return wrapper

    return decorate(fn) if fn is not None else decorate
