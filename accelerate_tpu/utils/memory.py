"""Memory helpers: OOM-retry batch-size finder, cache clearing.

Reference analogue: src/accelerate/utils/memory.py (find_executable_batch_size
:119 — the reference's only automatic failure-recovery loop; release_memory
:70; clear_device_cache :43). On TPU "OOM" is an XLA ``RESOURCE_EXHAUSTED``
raised at compile or first execution, so the decorator catches that instead
of torch's CUDA OOM strings.
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Callable, Optional


def clear_device_cache(garbage_collection: bool = False) -> None:
    """Drop cached compiled executables + live buffers where possible
    (reference: utils/memory.py:43)."""
    if garbage_collection:
        gc.collect()
    import jax

    jax.clear_caches()


def release_memory(*objects):
    """Del references and clear caches (reference: utils/memory.py:70).
    Returns a None per input so callers can rebind."""
    if len(objects) == 1 and isinstance(objects[0], list):
        objects = objects[0]
    objects = list(objects)
    for i in range(len(objects)):
        objects[i] = None
    gc.collect()
    clear_device_cache()
    return objects


def should_reduce_batch_size(exception: Exception) -> bool:
    """Heuristic OOM detection for XLA/TPU (reference: utils/memory.py:94
    matches CUDA OOM strings)."""
    statements = (
        "RESOURCE_EXHAUSTED",
        "Out of memory",
        "out of memory",
        "Attempting to reserve",
        "Ran out of memory",
        "exceeds the maximum",
        "HBM",
    )
    msg = str(exception)
    return any(s in msg for s in statements)


def find_executable_batch_size(
    function: Optional[Callable] = None, starting_batch_size: int = 128, reduce_batch_size_fn: Optional[Callable] = None
):
    """Decorator: call ``function(batch_size, *args)``; on OOM halve the
    batch size and retry (reference: utils/memory.py:119-184)."""
    if function is None:
        return functools.partial(
            find_executable_batch_size,
            starting_batch_size=starting_batch_size,
            reduce_batch_size_fn=reduce_batch_size_fn,
        )
    if reduce_batch_size_fn is None:
        reduce_batch_size_fn = lambda bs: bs // 2

    batch_size_box = {"value": starting_batch_size}

    @functools.wraps(function)
    def decorator(*args, **kwargs):
        nonlocal batch_size_box
        batch_size_box["value"] = starting_batch_size
        params = list(inspect.signature(function).parameters.keys())
        if not params or params[0] != "batch_size":
            raise TypeError(
                f"Batch size was passed into `{function.__name__}` as the first argument, but its signature "
                f"is {params} — it must accept `batch_size` first."
            )
        while True:
            if batch_size_box["value"] == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                return function(batch_size_box["value"], *args, **kwargs)
            except Exception as e:
                if should_reduce_batch_size(e):
                    clear_device_cache(garbage_collection=True)
                    batch_size_box["value"] = reduce_batch_size_fn(batch_size_box["value"])
                else:
                    raise

    return decorator


def get_device_memory_stats() -> dict:
    """Per-device live/limit bytes where the backend exposes them."""
    import jax

    stats = {}
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if s:
            stats[str(d)] = {
                "bytes_in_use": s.get("bytes_in_use"),
                "bytes_limit": s.get("bytes_limit"),
                "peak_bytes_in_use": s.get("peak_bytes_in_use"),
            }
    return stats
