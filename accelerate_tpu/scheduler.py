"""LR scheduler wrapper.

Reference analogue: src/accelerate/scheduler.py (98 LoC): step the scheduler
only when the optimizer actually stepped, and scale step count by
``num_processes`` unless ``split_batches`` (scheduler.py:54-84).

optax schedules are pure functions of the step counter, so "stepping" is
advancing a counter; the skip/scale semantics live here and the jitted fast
path reads ``schedule(step)`` directly.
"""

from __future__ import annotations

from typing import Callable, Optional, Union


class AcceleratedScheduler:
    """Wraps an optax schedule fn ``step -> lr`` (or any object exposing
    ``step()``/``get_last_lr()``)."""

    def __init__(
        self,
        scheduler: Union[Callable[[int], float], object],
        optimizers=None,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers] if optimizers else []
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.step_count = 0
        self._is_accelerate_prepared = False
        from .state import AcceleratorState, GradientState

        self.gradient_state = GradientState()
        self._num_data_shards = None
        # fp16 fast path: skip flags arrive as DEVICE scalars; coercing one
        # per boundary would stall the host on the in-flight step, so they
        # queue here and drain in one batched fetch when someone actually
        # reads the scheduler (get_last_lr/state_dict) or the queue fills
        self._pending_skips: list = []
        self._max_pending = 1024

    def _data_shards(self) -> int:
        if self._num_data_shards is None:
            from .state import AcceleratorState
            from .parallel.mesh import data_parallel_size

            state = AcceleratorState._shared_state
            if state.get("_initialized") and state.get("mesh") is not None:
                self._num_data_shards = data_parallel_size(state["mesh"])
            else:
                self._num_data_shards = 1
        return self._num_data_shards

    def step(self, *args, **kwargs):
        if not self.step_with_optimizer:
            self._advance(1)
            return
        # only step when gradients were synced (reference: scheduler.py:62)
        if not self.gradient_state.sync_gradients:
            return
        # skip when the optimizer skipped (fp16 overflow) — reference :69-75.
        # Device-array flags (fast path) are queued, not coerced: bool()
        # here would force a per-boundary device->host fetch.
        skips = [getattr(opt, "_step_was_skipped", False) for opt in self.optimizers]
        if any(not isinstance(s, bool) for s in skips):
            self._pending_skips.append(skips)
            if len(self._pending_skips) >= self._max_pending:
                self._drain()
            return
        if any(skips):
            return
        # one optimizer step consumed num_data_shards batches worth of data
        # (reference multiplies by num_processes, scheduler.py:78-84)
        self._advance(1 if self.split_batches else self._data_shards())

    def _drain(self):
        """Resolve queued device skip-flags in one batched fetch and apply
        the corresponding advances."""
        if not self._pending_skips:
            return
        import jax
        import numpy as np

        pending, self._pending_skips = self._pending_skips, []
        resolved = jax.device_get(pending)  # one transfer for the whole queue
        n = 1 if self.split_batches else self._data_shards()
        for skips in resolved:
            if not any(bool(np.asarray(s)) for s in skips):
                self._advance(n)

    def _advance(self, n: int):
        self.step_count += n
        if hasattr(self.scheduler, "step"):
            for _ in range(n):
                self.scheduler.step()

    def get_last_lr(self):
        self._drain()
        if hasattr(self.scheduler, "get_last_lr"):
            return self.scheduler.get_last_lr()
        return [float(self.scheduler(self.step_count))]

    def current_lr(self, step: Optional[int] = None) -> float:
        self._drain()
        s = self.step_count if step is None else step
        if callable(self.scheduler):
            return float(self.scheduler(s))
        return self.get_last_lr()[0]

    def state_dict(self) -> dict:
        self._drain()
        return {"step_count": self.step_count}

    def load_state_dict(self, state_dict: dict):
        self._pending_skips = []
        self.step_count = int(state_dict["step_count"])
