"""Serving-side counters: TTFT, tokens/sec, queue depth, KV utilisation,
preemptions — plus a Prometheus text-exposition dump.

The :class:`~accelerate_tpu.serving.ServingEngine` drives these hooks from
the places the events actually happen (submit, admit/first-token, decode
walk, retire, cancel, pool-blocked admission), so the numbers are exact
counts, not sampled approximations. Latency distributions (TTFT,
per-request e2e) are kept in bounded deques — a long-running server's
metrics memory is O(window), not O(requests).

``prometheus_text()`` renders the standard text exposition format
(``# HELP`` / ``# TYPE`` + samples) so a scrape endpoint is one
``web.Response(text=engine.metrics.prometheus_text())`` away; quantiles
are emitted as ``summary`` quantile samples over the retained window.
"""

from __future__ import annotations

import collections
import time
from typing import Optional

from .eventlog import EventLog

_PREFIX = "accelerate_tpu_serving"


def _pct(values, q: float) -> Optional[float]:
    vals = sorted(values)
    if not vals:
        return None
    k = max(0, min(len(vals) - 1, int(round(q / 100.0 * (len(vals) - 1)))))
    return vals[k]


def _render_prom(rows) -> str:
    """Render ``(name, mtype, help, suffix, label_str, value)`` rows as
    text exposition: one HELP/TYPE block per metric (first-seen order),
    then every sample of that metric — the grouping a multi-replica
    scrape needs."""
    by_name: dict = {}
    order = []
    for name, mtype, help_text, suffix, labels, value in rows:
        if name not in by_name:
            by_name[name] = (mtype, help_text, [])
            order.append(name)
        by_name[name][2].append((suffix, labels, value))
    lines = []
    for name in order:
        mtype, help_text, samples = by_name[name]
        lines.append(f"# HELP {_PREFIX}_{name} {help_text}")
        lines.append(f"# TYPE {_PREFIX}_{name} {mtype}")
        for suffix, labels, value in samples:
            if value is None:
                continue
            lines.append(f"{_PREFIX}_{name}{suffix}{labels} {value:g}")
    return "\n".join(lines) + "\n"


def fleet_prometheus_text(metrics) -> str:
    """One scrape body for N replicas' :class:`ServingMetrics`: a single
    HELP/TYPE block per metric with one ``replica``-labeled sample per
    replica — what a fleet exposes on its shared ``/metrics`` endpoint
    (aggregate with ``sum by`` in the scraper, or serve
    ``ServingMetrics.merge(...).prometheus_text()`` for a pre-merged
    view)."""
    rows = []
    for i, m in enumerate(metrics):
        if m.replica is None:
            m = _with_replica(m, f"r{i}")
        rows.extend(m._prom_samples())
    return _render_prom(rows)


def _with_replica(metrics: "ServingMetrics", name: str) -> "ServingMetrics":
    """Label an unlabeled instance for one render without mutating it."""
    import copy

    clone = copy.copy(metrics)
    clone.replica = name
    return clone


class ServingMetrics:
    """Counter/latency surface for one :class:`ServingEngine`.

    ``log`` (optional): mirror every snapshot to a telemetry
    :class:`EventLog` as ``serving.*`` counters, so a serving run and a
    training run summarize through the same CLI.

    ``replica`` (optional): a fleet replica name; when set, every
    Prometheus sample carries a ``replica="..."`` label so N replicas'
    engines scrape as one fleet view (:func:`fleet_prometheus_text`),
    and :meth:`merge` aggregates them into one fleet-level instance.
    """

    def __init__(
        self,
        engine=None,
        *,
        log: Optional[EventLog] = None,
        window: int = 1024,
        clock=time.monotonic,
        replica: Optional[str] = None,
    ):
        self._engine = engine
        self.log = log if log is not None else EventLog(None)
        self._clock = clock
        self.replica = replica
        # set by merge(): the source instances a fleet view aggregates
        # its live gauges (queue depth, tokens/sec) over
        self._sources: Optional[list] = None
        # monotonically increasing counters
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_cancelled = 0
        self.tokens_generated = 0
        self.prefills = 0
        self.preemptions = 0  # admission passes blocked on pool exhaustion
        # scheduler decisions (accelerate_tpu.scheduling)
        self.requests_shed = 0  # SLO load shedding (submit reject + queue-wait shed)
        self.requests_deprioritized = 0
        self.decode_preemptions = 0  # decoding slots evicted + requeued
        self.resumes = 0  # preempted requests resumed by recompute
        # cross-request prefix reuse (serving_fleet.RadixPrefixCache):
        # a hit means the request skipped re-prefilling that many shared
        # preamble tokens — the fleet's dominant p95-TTFT lever
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_evictions = 0
        self.prefix_registrations = 0
        self.prefix_tokens_reused = 0
        # fleet fault tolerance (serving_fleet): failover flow counters
        # and this replica's health level (0 healthy, 1 degraded,
        # 2 quarantined, 3 dead — a fleet view exposes the worst source)
        self.failovers_in = 0  # migrated requests imported by this engine
        self.failovers_out = 0  # in-flight requests migrated off this engine
        self.failovers_lost = 0  # in-flight requests unrecoverable at failover
        self.replica_errors = 0  # engine exceptions classified by the router
        self.replica_timeouts = 0  # tick wall-time SLO violations
        self._replica_state = 0
        # latency windows
        self.ttft_ms: collections.deque = collections.deque(maxlen=window)
        self.e2e_ms: collections.deque = collections.deque(maxlen=window)
        # inter-token latency: one sample per (request, tick) = elapsed
        # since the request's previous token delivery / tokens delivered
        # this tick — the per-token stream latency a client observes
        self.itl_ms: collections.deque = collections.deque(maxlen=window)
        # submit -> admission wait (the SLO the shed threshold guards)
        self.queue_wait_ms: collections.deque = collections.deque(maxlen=window)
        # per-inflight-request timing
        self._submit_ts: dict[int, float] = {}
        self._last_tok_ts: dict[int, float] = {}
        # tokens/sec over a sliding window of (ts, cumulative tokens)
        self._token_marks: collections.deque = collections.deque(maxlen=window)

    # ------------------------------------------------------------------ #
    # engine hooks
    # ------------------------------------------------------------------ #

    def on_submit(self, uid: int):
        self.requests_submitted += 1
        self._submit_ts[uid] = self._clock()

    def on_first_token(self, uid: int):
        """Called when a request's first generated token lands (the tail
        of its prefill) — the TTFT sample."""
        self.prefills += 1
        now = self._clock()
        self._last_tok_ts[uid] = now
        t0 = self._submit_ts.get(uid)
        if t0 is not None:
            self.ttft_ms.append((now - t0) * 1000.0)

    def on_admit(self, uid: int, priority: int = 0, queue_wait_ms: Optional[float] = None):
        """Queue-wait sample at the moment a request claims a slot."""
        if queue_wait_ms is not None:
            self.queue_wait_ms.append(queue_wait_ms)

    def on_tokens(self, n: int = 1):
        self.tokens_generated += n
        self._token_marks.append((self._clock(), self.tokens_generated))

    def on_tick_tokens(self, uid: int, n: int):
        """ITL sample: ``n`` tokens delivered to ``uid`` this tick."""
        now = self._clock()
        t0 = self._last_tok_ts.get(uid)
        if t0 is not None and n > 0:
            self.itl_ms.append((now - t0) * 1000.0 / n)
        self._last_tok_ts[uid] = now

    def on_complete(self, uid: int):
        self.requests_completed += 1
        self._last_tok_ts.pop(uid, None)
        t0 = self._submit_ts.pop(uid, None)
        if t0 is not None:
            self.e2e_ms.append((self._clock() - t0) * 1000.0)

    def on_cancel(self, uid: int):
        self.requests_cancelled += 1
        self._submit_ts.pop(uid, None)
        self._last_tok_ts.pop(uid, None)

    def on_pool_blocked(self):
        self.preemptions += 1

    def on_shed(self, uid: Optional[int]):
        """SLO load shed — submit-time reject (uid None) or a queued
        request dropped after blowing the wait threshold."""
        self.requests_shed += 1
        if uid is not None:
            self._submit_ts.pop(uid, None)

    def on_deprioritize(self, uid: Optional[int]):
        self.requests_deprioritized += 1

    def on_preempt_decode(self, uid: int):
        """A decoding slot was evicted and requeued; the preemption gap
        must not pollute the ITL window, so the chain restarts at the
        first post-resume delivery."""
        self.decode_preemptions += 1
        self._last_tok_ts.pop(uid, None)

    def on_resume(self, uid: int):
        self.resumes += 1
        self._last_tok_ts[uid] = self._clock()

    def on_prefix_hit(self, tokens_reused: int = 0):
        """A request matched a registered shared preamble and skipped
        re-prefilling ``tokens_reused`` tokens."""
        self.prefix_hits += 1
        self.prefix_tokens_reused += int(tokens_reused)

    def on_prefix_miss(self):
        self.prefix_misses += 1

    def on_prefix_evict(self):
        self.prefix_evictions += 1

    def on_prefix_register(self):
        self.prefix_registrations += 1

    def on_failover_in(self):
        """A migrated in-flight request was imported by this engine."""
        self.failovers_in += 1

    def on_failover_out(self):
        """An in-flight request was exported off this engine's replica."""
        self.failovers_out += 1

    def on_failover_lost(self):
        """An in-flight request could not be recovered at failover."""
        self.failovers_lost += 1

    def on_replica_error(self):
        self.replica_errors += 1

    def on_replica_timeout(self):
        self.replica_timeouts += 1

    def on_replica_state(self, level: int):
        """Router health transition: 0 healthy, 1 degraded, 2 quarantined,
        3 dead."""
        self._replica_state = int(level)

    # ------------------------------------------------------------------ #
    # read surface
    # ------------------------------------------------------------------ #

    @property
    def queue_depth(self) -> int:
        if self._sources:
            return sum(m.queue_depth for m in self._sources)
        return len(self._engine.queue) if self._engine is not None else 0

    @property
    def active_slots(self) -> int:
        if self._sources:
            return sum(m.active_slots for m in self._sources)
        return self._engine.active_count if self._engine is not None else 0

    @property
    def replica_state(self) -> int:
        """Health level of this replica (0 healthy, 1 degraded,
        2 quarantined, 3 dead); a fleet view reports its WORST source —
        the alerting-relevant aggregate."""
        if self._sources:
            return max(m.replica_state for m in self._sources)
        return self._replica_state

    @property
    def kv_block_utilization(self) -> Optional[float]:
        """Fraction of the paged pool in use (None in dense mode; a
        fleet view averages its paged replicas)."""
        if self._sources:
            utils = [m.kv_block_utilization for m in self._sources]
            utils = [u for u in utils if u is not None]
            return sum(utils) / len(utils) if utils else None
        if self._engine is None or not getattr(self._engine, "paged", False):
            return None
        total = self._engine._pcfg.num_blocks - 1  # minus the trash sink
        if total <= 0:
            return 0.0
        return 1.0 - self._engine._alloc.free_count / total

    def tokens_per_sec(self, window_s: float = 10.0) -> Optional[float]:
        """Decode throughput over the trailing ``window_s`` seconds of
        token marks (None until two marks exist; a fleet view sums its
        replicas' rates)."""
        if self._sources:
            rates = [m.tokens_per_sec(window_s) for m in self._sources]
            rates = [r for r in rates if r is not None]
            return sum(rates) if rates else None
        if len(self._token_marks) < 2:
            return None
        now = self._clock()
        marks = [(ts, tot) for ts, tot in self._token_marks if now - ts <= window_s]
        if len(marks) < 2:
            marks = list(self._token_marks)[-2:]
        (t0, c0), (t1, c1) = marks[0], marks[-1]
        if t1 <= t0:
            return None
        return (c1 - c0) / (t1 - t0)

    def snapshot(self) -> dict:
        """One flat dict of every metric — what the event log and the
        tracker forwarding consume."""
        snap = {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_cancelled": self.requests_cancelled,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "preemptions": self.preemptions,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "kv_block_utilization": self.kv_block_utilization,
            "tokens_per_sec": self.tokens_per_sec(),
            "ttft_ms_p50": _pct(self.ttft_ms, 50),
            "ttft_ms_p95": _pct(self.ttft_ms, 95),
            "e2e_ms_p50": _pct(self.e2e_ms, 50),
            "e2e_ms_p95": _pct(self.e2e_ms, 95),
            "itl_ms_p50": _pct(self.itl_ms, 50),
            "itl_ms_p95": _pct(self.itl_ms, 95),
            "queue_wait_ms_p50": _pct(self.queue_wait_ms, 50),
            "queue_wait_ms_p95": _pct(self.queue_wait_ms, 95),
            "requests_shed": self.requests_shed,
            "requests_deprioritized": self.requests_deprioritized,
            "decode_preemptions": self.decode_preemptions,
            "resumes": self.resumes,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_evictions": self.prefix_evictions,
            "prefix_registrations": self.prefix_registrations,
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "failovers_in": self.failovers_in,
            "failovers_out": self.failovers_out,
            "failovers_lost": self.failovers_lost,
            "replica_errors": self.replica_errors,
            "replica_timeouts": self.replica_timeouts,
            "replica_state": self.replica_state,
        }
        if self.replica is not None:
            snap["replica"] = self.replica
        return snap

    #: counters merge() sums and prometheus exposes as *_total samples
    _COUNTERS = (
        "requests_submitted", "requests_completed", "requests_cancelled",
        "tokens_generated", "prefills", "preemptions", "requests_shed",
        "requests_deprioritized", "decode_preemptions", "resumes",
        "prefix_hits", "prefix_misses", "prefix_evictions",
        "prefix_registrations", "prefix_tokens_reused",
        "failovers_in", "failovers_out", "failovers_lost",
        "replica_errors", "replica_timeouts",
    )
    _WINDOWS = ("ttft_ms", "e2e_ms", "itl_ms", "queue_wait_ms")

    @classmethod
    def merge(cls, metrics, replica: str = "fleet") -> "ServingMetrics":
        """One fleet-level view over N replicas' metrics: counters sum,
        latency windows pool (so fleet p50/p95 are quantiles over EVERY
        replica's samples, not an average of averages), and the live
        gauges (queue depth, active slots, tokens/sec) read through to
        the sources at scrape time. The result renders/scrapes exactly
        like a single engine's metrics."""
        metrics = list(metrics)
        out = cls(None, replica=replica)
        out._sources = metrics
        for name in cls._COUNTERS:
            setattr(out, name, sum(getattr(m, name) for m in metrics))
        for name in cls._WINDOWS:
            pooled = collections.deque(
                (v for m in metrics for v in getattr(m, name)),
                maxlen=sum(getattr(m, name).maxlen for m in metrics) or 1,
            )
            setattr(out, name, pooled)
        return out

    def emit(self):
        """Write the snapshot to the attached event log as ``serving.*``
        counters (no-op when the log is disabled). The ``replica`` name
        is attached as a tag on each counter, not emitted as a value."""
        tags = {"replica": self.replica} if self.replica is not None else {}
        for name, value in self.snapshot().items():
            if name != "replica" and value is not None:
                self.log.counter(f"serving.{name}", value, **tags)

    #: (metric name, type, help, attribute/window) rows the exposition
    #: renders — shared by the single-engine and fleet renderers so a
    #: fleet scrape emits ONE ``# HELP``/``# TYPE`` block per metric with
    #: a sample per replica (the Prometheus contract for labeled series).
    _PROM_COUNTERS = (
        ("requests_submitted_total", "Requests accepted by submit()", "requests_submitted"),
        ("requests_completed_total", "Requests retired with a result", "requests_completed"),
        ("requests_cancelled_total", "Requests cancelled mid-flight or queued", "requests_cancelled"),
        ("tokens_generated_total", "Generated tokens across all requests", "tokens_generated"),
        ("prefills_total", "Prompt prefills executed", "prefills"),
        ("preemptions_total", "Admission passes blocked on KV pool exhaustion", "preemptions"),
        ("requests_shed_total", "Requests rejected by SLO load shedding", "requests_shed"),
        ("requests_deprioritized_total", "Requests demoted by SLO load shedding", "requests_deprioritized"),
        ("decode_preemptions_total", "Decoding slots evicted and requeued", "decode_preemptions"),
        ("resumes_total", "Preempted requests resumed by recompute", "resumes"),
        ("prefix_hits_total", "Requests that reused a registered shared preamble", "prefix_hits"),
        ("prefix_misses_total", "Requests with no registered preamble match", "prefix_misses"),
        ("prefix_evictions_total", "Radix-cache prefix entries evicted (LRU)", "prefix_evictions"),
        ("prefix_registrations_total", "Shared preambles promoted into the radix cache", "prefix_registrations"),
        ("prefix_tokens_reused_total", "Prompt tokens served from cached prefixes (no re-prefill)", "prefix_tokens_reused"),
        ("failovers_in_total", "Migrated in-flight requests imported from a failed replica", "failovers_in"),
        ("failovers_out_total", "In-flight requests migrated off this replica at failure/drain", "failovers_out"),
        ("failovers_lost_total", "In-flight requests unrecoverable at failover", "failovers_lost"),
        ("replica_errors_total", "Engine exceptions classified by the fleet router", "replica_errors"),
        ("replica_timeouts_total", "Tick wall-time SLO violations", "replica_timeouts"),
    )
    _PROM_SUMMARIES = (
        ("ttft_ms", "Time to first token (ms)", "ttft_ms"),
        ("e2e_ms", "Request end-to-end latency (ms)", "e2e_ms"),
        ("itl_ms", "Inter-token latency (ms) per delivered token", "itl_ms"),
        ("queue_wait_ms", "Submit-to-admission queue wait (ms)", "queue_wait_ms"),
    )
    _PROM_GAUGES = (
        ("queue_depth", "Requests waiting for a slot", "queue_depth"),
        ("active_slots", "Slots currently decoding", "active_slots"),
        ("kv_block_utilization", "Fraction of the paged KV pool in use", "kv_block_utilization"),
        ("tokens_per_sec", "Decode throughput over the trailing window", "tokens_per_sec"),
        ("replica_state", "Replica health (0 healthy, 1 degraded, 2 quarantined, 3 dead)", "replica_state"),
    )

    def _label_str(self, extra: Optional[dict] = None) -> str:
        labels = {}
        if self.replica is not None:
            labels["replica"] = self.replica
        labels.update(extra or {})
        if not labels:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
        return "{" + inner + "}"

    def _prom_samples(self):
        """``(name, mtype, help, suffix, label_str, value)`` rows for this
        instance (None values are dropped at render time)."""
        rows = []
        for name, help_text, attr in self._PROM_COUNTERS:
            rows.append((name, "counter", help_text, "", self._label_str(), getattr(self, attr)))
        for name, help_text, attr in self._PROM_GAUGES:
            val = getattr(self, attr)
            if callable(val):
                val = val()
            rows.append((name, "gauge", help_text, "", self._label_str(), val))
        for name, help_text, attr in self._PROM_SUMMARIES:
            window = getattr(self, attr)
            rows.append((name, "summary", help_text, "",
                         self._label_str({"quantile": "0.5"}), _pct(window, 50)))
            rows.append((name, "summary", help_text, "",
                         self._label_str({"quantile": "0.95"}), _pct(window, 95)))
            rows.append((name, "summary", help_text, "_count", self._label_str(), len(window)))
        return rows

    def prometheus_text(self) -> str:
        """Prometheus text exposition (v0.0.4) of the snapshot. With
        :attr:`replica` set, every sample carries the ``replica`` label."""
        return _render_prom(self._prom_samples())
