"""Serving-side counters: TTFT, tokens/sec, queue depth, KV utilisation,
preemptions — plus a Prometheus text-exposition dump.

The :class:`~accelerate_tpu.serving.ServingEngine` drives these hooks from
the places the events actually happen (submit, admit/first-token, decode
walk, retire, cancel, pool-blocked admission), so the numbers are exact
counts, not sampled approximations. Latency distributions (TTFT,
per-request e2e) are kept in bounded deques — a long-running server's
metrics memory is O(window), not O(requests).

``prometheus_text()`` renders the standard text exposition format
(``# HELP`` / ``# TYPE`` + samples) so a scrape endpoint is one
``web.Response(text=engine.metrics.prometheus_text())`` away; quantiles
are emitted as ``summary`` quantile samples over the retained window.
"""

from __future__ import annotations

import collections
import time
from typing import Optional

from .eventlog import EventLog

_PREFIX = "accelerate_tpu_serving"


def _pct(values, q: float) -> Optional[float]:
    vals = sorted(values)
    if not vals:
        return None
    k = max(0, min(len(vals) - 1, int(round(q / 100.0 * (len(vals) - 1)))))
    return vals[k]


class ServingMetrics:
    """Counter/latency surface for one :class:`ServingEngine`.

    ``log`` (optional): mirror every snapshot to a telemetry
    :class:`EventLog` as ``serving.*`` counters, so a serving run and a
    training run summarize through the same CLI.
    """

    def __init__(self, engine=None, *, log: Optional[EventLog] = None, window: int = 1024, clock=time.monotonic):
        self._engine = engine
        self.log = log if log is not None else EventLog(None)
        self._clock = clock
        # monotonically increasing counters
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_cancelled = 0
        self.tokens_generated = 0
        self.prefills = 0
        self.preemptions = 0  # admission passes blocked on pool exhaustion
        # scheduler decisions (accelerate_tpu.scheduling)
        self.requests_shed = 0  # SLO load shedding (submit reject + queue-wait shed)
        self.requests_deprioritized = 0
        self.decode_preemptions = 0  # decoding slots evicted + requeued
        self.resumes = 0  # preempted requests resumed by recompute
        # latency windows
        self.ttft_ms: collections.deque = collections.deque(maxlen=window)
        self.e2e_ms: collections.deque = collections.deque(maxlen=window)
        # inter-token latency: one sample per (request, tick) = elapsed
        # since the request's previous token delivery / tokens delivered
        # this tick — the per-token stream latency a client observes
        self.itl_ms: collections.deque = collections.deque(maxlen=window)
        # submit -> admission wait (the SLO the shed threshold guards)
        self.queue_wait_ms: collections.deque = collections.deque(maxlen=window)
        # per-inflight-request timing
        self._submit_ts: dict[int, float] = {}
        self._last_tok_ts: dict[int, float] = {}
        # tokens/sec over a sliding window of (ts, cumulative tokens)
        self._token_marks: collections.deque = collections.deque(maxlen=window)

    # ------------------------------------------------------------------ #
    # engine hooks
    # ------------------------------------------------------------------ #

    def on_submit(self, uid: int):
        self.requests_submitted += 1
        self._submit_ts[uid] = self._clock()

    def on_first_token(self, uid: int):
        """Called when a request's first generated token lands (the tail
        of its prefill) — the TTFT sample."""
        self.prefills += 1
        now = self._clock()
        self._last_tok_ts[uid] = now
        t0 = self._submit_ts.get(uid)
        if t0 is not None:
            self.ttft_ms.append((now - t0) * 1000.0)

    def on_admit(self, uid: int, priority: int = 0, queue_wait_ms: Optional[float] = None):
        """Queue-wait sample at the moment a request claims a slot."""
        if queue_wait_ms is not None:
            self.queue_wait_ms.append(queue_wait_ms)

    def on_tokens(self, n: int = 1):
        self.tokens_generated += n
        self._token_marks.append((self._clock(), self.tokens_generated))

    def on_tick_tokens(self, uid: int, n: int):
        """ITL sample: ``n`` tokens delivered to ``uid`` this tick."""
        now = self._clock()
        t0 = self._last_tok_ts.get(uid)
        if t0 is not None and n > 0:
            self.itl_ms.append((now - t0) * 1000.0 / n)
        self._last_tok_ts[uid] = now

    def on_complete(self, uid: int):
        self.requests_completed += 1
        self._last_tok_ts.pop(uid, None)
        t0 = self._submit_ts.pop(uid, None)
        if t0 is not None:
            self.e2e_ms.append((self._clock() - t0) * 1000.0)

    def on_cancel(self, uid: int):
        self.requests_cancelled += 1
        self._submit_ts.pop(uid, None)
        self._last_tok_ts.pop(uid, None)

    def on_pool_blocked(self):
        self.preemptions += 1

    def on_shed(self, uid: Optional[int]):
        """SLO load shed — submit-time reject (uid None) or a queued
        request dropped after blowing the wait threshold."""
        self.requests_shed += 1
        if uid is not None:
            self._submit_ts.pop(uid, None)

    def on_deprioritize(self, uid: Optional[int]):
        self.requests_deprioritized += 1

    def on_preempt_decode(self, uid: int):
        """A decoding slot was evicted and requeued; the preemption gap
        must not pollute the ITL window, so the chain restarts at the
        first post-resume delivery."""
        self.decode_preemptions += 1
        self._last_tok_ts.pop(uid, None)

    def on_resume(self, uid: int):
        self.resumes += 1
        self._last_tok_ts[uid] = self._clock()

    # ------------------------------------------------------------------ #
    # read surface
    # ------------------------------------------------------------------ #

    @property
    def queue_depth(self) -> int:
        return len(self._engine.queue) if self._engine is not None else 0

    @property
    def active_slots(self) -> int:
        return self._engine.active_count if self._engine is not None else 0

    @property
    def kv_block_utilization(self) -> Optional[float]:
        """Fraction of the paged pool in use (None in dense mode)."""
        if self._engine is None or not getattr(self._engine, "paged", False):
            return None
        total = self._engine._pcfg.num_blocks - 1  # minus the trash sink
        if total <= 0:
            return 0.0
        return 1.0 - self._engine._alloc.free_count / total

    def tokens_per_sec(self, window_s: float = 10.0) -> Optional[float]:
        """Decode throughput over the trailing ``window_s`` seconds of
        token marks (None until two marks exist)."""
        if len(self._token_marks) < 2:
            return None
        now = self._clock()
        marks = [(ts, tot) for ts, tot in self._token_marks if now - ts <= window_s]
        if len(marks) < 2:
            marks = list(self._token_marks)[-2:]
        (t0, c0), (t1, c1) = marks[0], marks[-1]
        if t1 <= t0:
            return None
        return (c1 - c0) / (t1 - t0)

    def snapshot(self) -> dict:
        """One flat dict of every metric — what the event log and the
        tracker forwarding consume."""
        snap = {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_cancelled": self.requests_cancelled,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "preemptions": self.preemptions,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "kv_block_utilization": self.kv_block_utilization,
            "tokens_per_sec": self.tokens_per_sec(),
            "ttft_ms_p50": _pct(self.ttft_ms, 50),
            "ttft_ms_p95": _pct(self.ttft_ms, 95),
            "e2e_ms_p50": _pct(self.e2e_ms, 50),
            "e2e_ms_p95": _pct(self.e2e_ms, 95),
            "itl_ms_p50": _pct(self.itl_ms, 50),
            "itl_ms_p95": _pct(self.itl_ms, 95),
            "queue_wait_ms_p50": _pct(self.queue_wait_ms, 50),
            "queue_wait_ms_p95": _pct(self.queue_wait_ms, 95),
            "requests_shed": self.requests_shed,
            "requests_deprioritized": self.requests_deprioritized,
            "decode_preemptions": self.decode_preemptions,
            "resumes": self.resumes,
        }
        return snap

    def emit(self):
        """Write the snapshot to the attached event log as ``serving.*``
        counters (no-op when the log is disabled)."""
        for name, value in self.snapshot().items():
            if value is not None:
                self.log.counter(f"serving.{name}", value)

    def prometheus_text(self) -> str:
        """Prometheus text exposition (v0.0.4) of the snapshot."""
        lines = []

        def metric(name, mtype, help_text, samples):
            lines.append(f"# HELP {_PREFIX}_{name} {help_text}")
            lines.append(f"# TYPE {_PREFIX}_{name} {mtype}")
            for labels, value in samples:
                if value is None:
                    continue
                lines.append(f"{_PREFIX}_{name}{labels} {value:g}")

        metric("requests_submitted_total", "counter", "Requests accepted by submit()",
               [("", self.requests_submitted)])
        metric("requests_completed_total", "counter", "Requests retired with a result",
               [("", self.requests_completed)])
        metric("requests_cancelled_total", "counter", "Requests cancelled mid-flight or queued",
               [("", self.requests_cancelled)])
        metric("tokens_generated_total", "counter", "Generated tokens across all requests",
               [("", self.tokens_generated)])
        metric("prefills_total", "counter", "Prompt prefills executed",
               [("", self.prefills)])
        metric("preemptions_total", "counter", "Admission passes blocked on KV pool exhaustion",
               [("", self.preemptions)])
        metric("requests_shed_total", "counter", "Requests rejected by SLO load shedding",
               [("", self.requests_shed)])
        metric("requests_deprioritized_total", "counter", "Requests demoted by SLO load shedding",
               [("", self.requests_deprioritized)])
        metric("decode_preemptions_total", "counter", "Decoding slots evicted and requeued",
               [("", self.decode_preemptions)])
        metric("resumes_total", "counter", "Preempted requests resumed by recompute",
               [("", self.resumes)])
        metric("queue_depth", "gauge", "Requests waiting for a slot",
               [("", self.queue_depth)])
        metric("active_slots", "gauge", "Slots currently decoding",
               [("", self.active_slots)])
        util = self.kv_block_utilization
        metric("kv_block_utilization", "gauge", "Fraction of the paged KV pool in use",
               [("", util)])
        metric("tokens_per_sec", "gauge", "Decode throughput over the trailing window",
               [("", self.tokens_per_sec())])
        metric("ttft_ms", "summary", "Time to first token (ms)",
               [('{quantile="0.5"}', _pct(self.ttft_ms, 50)),
                ('{quantile="0.95"}', _pct(self.ttft_ms, 95)),
                ("_count", len(self.ttft_ms))])
        metric("e2e_ms", "summary", "Request end-to-end latency (ms)",
               [('{quantile="0.5"}', _pct(self.e2e_ms, 50)),
                ('{quantile="0.95"}', _pct(self.e2e_ms, 95)),
                ("_count", len(self.e2e_ms))])
        metric("itl_ms", "summary", "Inter-token latency (ms) per delivered token",
               [('{quantile="0.5"}', _pct(self.itl_ms, 50)),
                ('{quantile="0.95"}', _pct(self.itl_ms, 95)),
                ("_count", len(self.itl_ms))])
        metric("queue_wait_ms", "summary", "Submit-to-admission queue wait (ms)",
               [('{quantile="0.5"}', _pct(self.queue_wait_ms, 50)),
                ('{quantile="0.95"}', _pct(self.queue_wait_ms, 95)),
                ("_count", len(self.queue_wait_ms))])
        return "\n".join(lines) + "\n"
