"""Bytes-on-wire counters: measure collective traffic from the COMPILED
program and cross-check it against the cost model's prediction.

The static tier (``analysis.costmodel.collect_traffic``) prices the
collectives *the author wrote* in the jaxpr; this module counts the
collectives that actually survived compilation — GSPMD both inserts
reductions the jaxpr never shows (the implicit data-parallel grad
all-reduce) and elides ones it can prove redundant. Parsing the
post-partitioning HLO is therefore a genuinely independent measurement:
``measured ~= predicted`` is the cross-check that keeps the wire-byte
model honest (the ``perf_model_drift`` discipline applied to bytes), and
both sides price through the SAME ring formulas
(``analysis.costmodel.ring_wire_bytes``) so a disagreement means missing
or phantom traffic, never unit drift.

Usage (what ``benchmarks/bench_zero1.py`` does)::

    compiled = step._jitted.lower(*sample_args).compile()
    measured = hlo_wire_bytes(compiled.as_text())
    telemetry.record_wire_bytes(predicted, measured["total"], label="train_step")

Pure text parsing — no jax import, no backend touch.
"""

from __future__ import annotations

import re
from typing import Optional

#: HLO collective opcode -> costmodel primitive (ring-formula key)
_HLO_TO_PRIM = {
    "all-reduce": "psum",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "ppermute",
}

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "pred": 1,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[\d+\]")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}")


def _result_bytes(result: str) -> tuple:
    """(total payload bytes, {dtype: bytes}) over every shape in the
    result portion (tuples sum their members)."""
    total = 0
    by_dtype: dict[str, int] = {}
    for dtype, dims in _SHAPE_RE.findall(result):
        if dtype not in _DTYPE_BYTES:
            continue
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        nbytes = elems * _DTYPE_BYTES[dtype]
        total += nbytes
        by_dtype[dtype] = by_dtype.get(dtype, 0) + nbytes
    return total, by_dtype


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(line)
    if m:  # [num_groups, group_size] <= [total]
        return int(m.group(2))
    m = _PAIRS_RE.search(line)
    if m:
        # collective-permute carries source_target_pairs, not
        # replica_groups: the "group" is the permutation cycle (a ring
        # handoff over an n-axis is n pairs per ring; follow one cycle)
        nxt = {}
        for pair in m.group(1).split("},{"):
            src, dst = pair.strip("{}").split(",")
            nxt[int(src)] = int(dst)
        start = min(nxt)
        cur, hops = nxt[start], 1
        while cur != start and cur in nxt and hops <= len(nxt):
            cur, hops = nxt[cur], hops + 1
        return hops
    return default


def hlo_collective_sites(hlo_text: str, *, default_group: int = 1) -> list[dict]:
    """Every collective instruction in a compiled HLO module:
    ``{op, prim, result_bytes, group_size}``.

    Plain string splitting, not one grand regex: the result portion may
    be a tuple interleaved with ``/*index=N*/`` comments (XLA's tuple
    all-to-all form — one buffer per split chunk; summing every shape in
    the tuple recovers the full payload). ``-done`` halves of async pairs
    are skipped (the ``-start`` carries the payload)."""
    sites = []
    for line in hlo_text.splitlines():
        if "-done(" in line or "=" not in line:
            continue
        for op in _HLO_TO_PRIM:
            hit = None
            for suffix in ("(", "-start("):
                idx = line.find(f" {op}{suffix}")
                if idx >= 0:
                    hit = idx
                    break
            if hit is None:
                continue
            eq = line.find("= ")
            if eq < 0 or eq > hit:
                continue
            result = line[eq + 2 : hit]
            nbytes, by_dtype = _result_bytes(result)
            sites.append(
                {
                    "op": op,
                    "prim": _HLO_TO_PRIM[op],
                    "result_bytes": nbytes,
                    "dtypes": by_dtype,
                    "group_size": _group_size(line, default_group),
                }
            )
            break
    return sites


def hlo_wire_bytes(hlo_text: str, *, default_group: Optional[int] = None) -> dict:
    """Per-device ring wire bytes the compiled program moves per
    execution, measured from its HLO text and priced through
    ``analysis.costmodel.ring_wire_bytes`` (the shared formulas).

    Operand conventions per op: an all-reduce's result IS the full
    payload; an all-gather's result is the full gathered payload (its
    per-shard input is ``result/n``); a reduce-scatter's result is the
    shard (full payload ``result*n``); all-to-all and permute move their
    own size. Returns ``{"total": int, "by_primitive": {...},
    "sites": [...]}``."""
    from ..analysis.costmodel import ring_wire_bytes

    sites = hlo_collective_sites(hlo_text, default_group=default_group or 1)
    by_prim: dict[str, int] = {}
    total = 0
    for s in sites:
        n = s["group_size"] if default_group is None else max(s["group_size"], default_group)
        if n <= 1:
            continue
        payload = s["result_bytes"]
        if s["prim"] == "reduce_scatter":
            payload *= n
        wire = ring_wire_bytes(s["prim"], payload, n)
        s["wire_bytes"] = wire
        by_prim[s["prim"]] = by_prim.get(s["prim"], 0) + wire
        total += wire
    return {"total": int(total), "by_primitive": by_prim, "sites": sites}


#: requested compression-scheme name -> expected wire payload width
_WIRE_DTYPE_WIDTH = {"bf16": 2, "f16": 2, "fp8": 1, "f8": 1, "int8": 1, "s8": 1}


def wire_dtype_upcast(sites, requested_dtype: str) -> Optional[dict]:
    """Did the compiled program's dominant collective move a WIDER dtype
    than the compression scheme requested? Some backends upcast narrow
    collectives during lowering (XLA:CPU runs bf16 all-reduces in f32),
    which silently erases the wire saving the scheme was chosen for —
    TPU backends keep the narrow dtype on the wire.

    ``sites`` is :func:`hlo_collective_sites` output (or the ``sites``
    list of :func:`hlo_wire_bytes`). Only the payload-dominant site is
    judged: tiny control collectives (an f32 loss pmean, a grad-norm
    psum) legitimately stay wide next to a quantized gradient leg.
    Returns ``{"requested", "requested_bytes", "measured_dtype",
    "measured_bytes", "site_bytes"}`` when an upcast is detected, else
    None."""
    want = _WIRE_DTYPE_WIDTH.get(str(requested_dtype).lower())
    if want is None or not sites:
        return None
    dominant = max(sites, key=lambda s: s.get("result_bytes", 0))
    dtypes = dominant.get("dtypes") or {}
    if not dtypes:
        return None
    # the dominant site's dominant dtype (a fused tuple may mix)
    dtype = max(dtypes, key=dtypes.get)
    width = _DTYPE_BYTES.get(dtype, 0)
    if width <= want:
        return None
    return {
        "requested": str(requested_dtype),
        "requested_bytes": want,
        "measured_dtype": dtype,
        "measured_bytes": width,
        "site_bytes": int(dominant.get("result_bytes", 0)),
    }
