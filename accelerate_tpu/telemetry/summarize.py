"""Turn a telemetry JSONL file into the run's story: step-time
percentiles, compile vs execute vs data-wait, recompile count, MFU, HBM
peak (observed AND statically predicted), and serving counters.

This is the offline half of the subsystem — everything here works on a
plain list of parsed records, no jax, no backend. The
``accelerate-tpu telemetry summarize`` CLI is a thin shell over
:func:`summarize` + :func:`render_text`.
"""

from __future__ import annotations

from typing import Optional

from .eventlog import read_events


def _pct(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return round(sorted_vals[k], 3)


def _mean(vals) -> Optional[float]:
    vals = list(vals)
    return round(sum(vals) / len(vals), 3) if vals else None


def summarize(events: list[dict]) -> dict:
    """Aggregate parsed telemetry records into one report dict. Sections
    (``steps`` / ``hbm`` / ``serving``) appear only when the run emitted
    the corresponding records, so training-only and serving-only files
    both summarize cleanly."""
    report: dict = {"events": len(events)}

    steps = [e for e in events if e.get("kind") == "span" and e.get("name") == "step"]
    if steps:
        steady = [s for s in steps if not s.get("compile")]
        durs = sorted(s.get("dur_ms", 0.0) for s in steady)
        compile_ms = sum(s.get("dispatch_ms", 0.0) for s in steps if s.get("compile"))
        recompiles = [e for e in events if e.get("kind") == "event" and e.get("name") == "recompile"]
        mfus = [s["mfu"] for s in steady if "mfu" in s]
        total = sum(s.get("dur_ms", 0.0) for s in steady)
        busy = sum(s.get("dispatch_ms", 0.0) + s.get("execute_ms", 0.0) for s in steady)
        static_step = next(
            (e for e in events if e.get("kind") == "event" and e.get("name") == "perf_static_estimate"),
            None,
        )
        perf_drift = [
            e for e in events if e.get("kind") == "event" and e.get("name") == "perf_model_drift"
        ]
        report["steps"] = {
            "count": len(steps),
            "steady_count": len(steady),
            "p50_step_ms": _pct(durs, 50),
            "p95_step_ms": _pct(durs, 95),
            "mean_data_wait_ms": _mean(s.get("data_wait_ms", 0.0) for s in steady),
            "mean_dispatch_ms": _mean(s.get("dispatch_ms", 0.0) for s in steady),
            "mean_execute_ms": _mean(s.get("execute_ms", 0.0) for s in steady),
            "compile_ms": round(compile_ms, 3),
            "recompiles": len(recompiles),
            "recompile_details": [
                {"step": e.get("step"), "changed": e.get("changed")} for e in recompiles
            ],
            "goodput": round(min(1.0, busy / total), 4) if total > 0 else None,
            "mfu": round(sum(mfus) / len(mfus), 5) if mfus else None,
            # static roofline cross-check (perf-check seeds the estimate,
            # StepTelemetry emits perf_model_drift on disagreement)
            "static_step_ms": static_step.get("predicted_ms") if static_step else None,
            "perf_drift_events": [
                {
                    "predicted_ms": e.get("predicted_ms"),
                    "observed_busy_ms": e.get("observed_busy_ms"),
                    "rel_error": e.get("rel_error"),
                }
                for e in perf_drift
            ],
        }

    hbm_counters = [e for e in events if e.get("kind") == "counter" and e.get("name") == "hbm_peak_bytes"]
    static = next(
        (e for e in events if e.get("kind") == "event" and e.get("name") == "hbm_static_estimate"), None
    )
    drift = [e for e in events if e.get("kind") == "event" and e.get("name") == "hbm_drift"]
    if hbm_counters or static:
        observed = max((e.get("value", 0) for e in hbm_counters), default=None)
        limits = [e.get("bytes_limit") for e in hbm_counters if e.get("bytes_limit")]
        report["hbm"] = {
            "observed_peak_bytes": observed,
            "static_peak_bytes": static.get("bytes") if static else None,
            "bytes_limit": max(limits) if limits else None,
            "headroom_bytes": (max(limits) - observed) if (limits and observed is not None) else None,
            "drift_events": [
                {
                    "observed_peak_bytes": e.get("observed_peak_bytes"),
                    "static_peak_bytes": e.get("static_peak_bytes"),
                    "rel_error": e.get("rel_error"),
                }
                for e in drift
            ],
        }

    serving = {}
    for e in events:
        if e.get("kind") == "counter" and str(e.get("name", "")).startswith("serving."):
            serving[e["name"][len("serving."):]] = e.get("value")  # last write wins
    if serving:
        report["serving"] = serving

    # scheduler decisions (serving.py + scheduling.py): every admit /
    # shed / preempt_decode / resume lands as one event with priority and
    # queue wait attached, so the report can say WHICH class paid
    sched = {name: [e for e in events if e.get("kind") == "event" and e.get("name") == name]
             for name in ("admit", "shed", "preempt_decode", "resume")}
    if any(sched.values()):
        waits = [e["queue_wait_ms"] for e in sched["admit"] if e.get("queue_wait_ms") is not None]
        report["scheduler"] = {
            "admitted": len(sched["admit"]),
            "shed": len(sched["shed"]),
            "preempted": len(sched["preempt_decode"]),
            "resumed": len(sched["resume"]),
            "mean_queue_wait_ms": _mean(waits),
            "p95_queue_wait_ms": _pct(sorted(waits), 95),
            "shed_by_priority": {
                str(p): sum(1 for e in sched["shed"] if e.get("priority") == p)
                for p in sorted({e.get("priority") for e in sched["shed"]})
            },
        }

    # compile cache (aot/): hit/miss/deserialize + per-bucket serving builds
    cc_hits = [e for e in events if e.get("kind") == "event" and e.get("name") == "compile_cache_hit"]
    cc_miss = [e for e in events if e.get("kind") == "event" and e.get("name") == "compile_cache_miss"]
    cc_rej = [e for e in events if e.get("kind") == "event" and e.get("name") == "compile_cache_reject"]
    buckets = [e for e in events if e.get("kind") == "event" and e.get("name") == "serving_bucket_compile"]
    if cc_hits or cc_miss or cc_rej or buckets:
        report["compile_cache"] = {
            "hits": len(cc_hits),
            "disk_hits": sum(1 for e in cc_hits if e.get("source") == "disk"),
            "misses": len(cc_miss),
            "rejected": len(cc_rej),
            "compile_ms": round(sum(e.get("compile_ms", 0.0) for e in cc_miss), 3),
            "deserialize_ms": round(sum(e.get("deserialize_ms", 0.0) for e in cc_hits), 3),
            "bucket_compiles": [
                {"program": e.get("program"), "bucket": e.get("bucket"), "compile_ms": e.get("compile_ms")}
                for e in buckets
            ],
        }

    # non-finite watchdog (the runtime counterpart of numerics TPU602):
    # the latched `nonfinite` event + the fp16 loss-scale trajectory
    nonfinite = [e for e in events if e.get("kind") == "event" and e.get("name") == "nonfinite"]
    scales = [e for e in events if e.get("kind") == "event" and e.get("name") == "loss_scale"]
    if nonfinite or scales:
        scale_vals = [e.get("scale") for e in scales if e.get("scale") is not None]
        report["nonfinite"] = {
            "events": [
                {
                    "step": e.get("step"),
                    "leaf": e.get("leaf"),
                    "value": e.get("value"),
                    "loss_scale": e.get("loss_scale"),
                }
                for e in nonfinite
            ],
            "loss_scale": {
                "current": scale_vals[-1] if scale_vals else None,
                "min": min(scale_vals) if scale_vals else None,
                "max": max(scale_vals) if scale_vals else None,
                "backoffs": max((e.get("backoffs", 0) for e in scales), default=0),
                "changes": len(scales),
            }
            if scales
            else None,
        }

    # request traces (telemetry.trace): reconstruct completed traces from
    # their span + trace_complete records, decompose the critical path,
    # and surface the latched trace_drift warnings + flight-recorder dumps
    trace_complete = [
        e for e in events if e.get("kind") == "event" and e.get("name") == "trace_complete"
    ]
    if trace_complete:
        from .critpath import decompose
        from .trace import traces_from_events

        traces = traces_from_events(events)
        tdrift = [e for e in events if e.get("kind") == "event" and e.get("name") == "trace_drift"]
        dumps = [e for e in events if e.get("kind") == "event" and e.get("name") == "flight_dump"]
        decomp = decompose(traces)
        report["traces"] = {
            "count": decomp["count"],
            "completed": decomp["completed"],
            "by_class": decomp["by_class"],
            "drift_events": [
                {
                    "segment": e.get("segment"),
                    "check": e.get("check"),
                    "observed": e.get("observed"),
                    "predicted": e.get("predicted"),
                    "rel_error": e.get("rel_error"),
                    "trace": e.get("trace"),
                }
                for e in tdrift
            ],
            "flight_dumps": len(dumps),
        }

    warnings = [
        e for e in events
        if e.get("kind") == "event" and e.get("severity") in ("warning", "error")
    ]
    report["warnings"] = len(warnings)
    return report


def summarize_file(path: str) -> dict:
    return summarize(read_events(path))


def _human_bytes(n) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n:.1f} TiB"


def render_text(report: dict) -> str:
    """Human-readable report (the ``--format text`` default)."""
    lines = [f"telemetry summary ({report.get('events', 0)} records, "
             f"{report.get('warnings', 0)} warnings)"]
    steps = report.get("steps")
    if steps:
        lines.append("  steps:")
        lines.append(
            f"    step time         : p50 {steps['p50_step_ms']} ms / p95 {steps['p95_step_ms']} ms "
            f"({steps['steady_count']} steady of {steps['count']})"
        )
        lines.append(
            f"    split (mean)      : data-wait {steps['mean_data_wait_ms']} ms | "
            f"dispatch {steps['mean_dispatch_ms']} ms | execute {steps['mean_execute_ms']} ms"
        )
        lines.append(f"    compile           : {steps['compile_ms']} ms")
        lines.append(f"    recompiles        : {steps['recompiles']}")
        for d in steps.get("recompile_details", []):
            for change in d.get("changed") or []:
                lines.append(f"      step {d.get('step')}: {change}")
        if steps.get("goodput") is not None:
            lines.append(f"    goodput           : {steps['goodput']:.1%}")
        if steps.get("mfu") is not None:
            lines.append(f"    MFU               : {steps['mfu']:.1%}")
        if steps.get("static_step_ms") is not None:
            lines.append(f"    static prediction : {steps['static_step_ms']} ms (perf-check roofline)")
        for d in steps.get("perf_drift_events", []):
            lines.append(
                f"    DRIFT: observed busy {d['observed_busy_ms']} ms vs "
                f"predicted {d['predicted_ms']} ms ({d['rel_error']:.0%} off)"
            )
    hbm = report.get("hbm")
    if hbm:
        lines.append("  hbm:")
        lines.append(f"    observed peak     : {_human_bytes(hbm['observed_peak_bytes'])}")
        lines.append(f"    static estimate   : {_human_bytes(hbm['static_peak_bytes'])}")
        if hbm.get("headroom_bytes") is not None:
            lines.append(f"    headroom          : {_human_bytes(hbm['headroom_bytes'])}")
        for d in hbm.get("drift_events", []):
            lines.append(
                f"    DRIFT: observed {_human_bytes(d['observed_peak_bytes'])} vs "
                f"static {_human_bytes(d['static_peak_bytes'])} ({d['rel_error']:.0%} off)"
            )
    serving = report.get("serving")
    if serving:
        lines.append("  serving:")
        order = (
            "requests_submitted", "requests_completed", "requests_cancelled",
            "tokens_generated", "tokens_per_sec", "ttft_ms_p50", "ttft_ms_p95",
            "queue_depth", "kv_block_utilization", "preemptions",
        )
        for key in order:
            if key in serving and serving[key] is not None:
                val = serving[key]
                lines.append(f"    {key:<18}: {val:.3f}" if isinstance(val, float) else f"    {key:<18}: {val}")
        for key, val in serving.items():
            if key not in order and val is not None:
                lines.append(f"    {key:<18}: {val}")
    sched = report.get("scheduler")
    if sched:
        lines.append("  scheduler:")
        lines.append(
            f"    decisions         : {sched['admitted']} admitted | {sched['shed']} shed | "
            f"{sched['preempted']} preempted | {sched['resumed']} resumed"
        )
        if sched.get("mean_queue_wait_ms") is not None:
            lines.append(
                f"    queue wait        : mean {sched['mean_queue_wait_ms']} ms / "
                f"p95 {sched['p95_queue_wait_ms']} ms"
            )
        for prio, n in (sched.get("shed_by_priority") or {}).items():
            lines.append(f"    shed priority {prio}   : {n}")
    cc = report.get("compile_cache")
    if cc:
        lines.append("  compile cache:")
        lines.append(
            f"    hits              : {cc['hits']} ({cc['disk_hits']} from disk, "
            f"{cc['deserialize_ms']} ms deserializing)"
        )
        lines.append(f"    misses            : {cc['misses']} ({cc['compile_ms']} ms compiling)")
        if cc.get("rejected"):
            lines.append(f"    rejected entries  : {cc['rejected']} (stale/poisoned, healed)")
        for b in cc.get("bucket_compiles", []):
            lines.append(
                f"    bucket {b.get('program')}[{b.get('bucket')}]: built in {b.get('compile_ms')} ms"
            )
    traces = report.get("traces")
    if traces:
        lines.append("  traces:")
        lines.append(
            f"    requests          : {traces['count']} traced, {traces['completed']} completed ok"
        )
        if traces.get("by_class"):
            lines.append("    segment         count   p50_ms    p95_ms    total_ms  share")
            for name, row in traces["by_class"].items():
                lines.append(
                    f"    {name:<15} {row['count']:>5} {row['p50_ms']:>9.3f} {row['p95_ms']:>9.3f}"
                    f" {row['total_ms']:>11.3f}  {row['share']:.1%}"
                )
        for d in traces.get("drift_events", []):
            lines.append(
                f"    DRIFT: {d['segment']} vs {d['check']}: observed {d['observed']} "
                f"vs predicted {d['predicted']} ({d['rel_error']:.0%} off, trace {d['trace']})"
            )
        if traces.get("flight_dumps"):
            lines.append(f"    flight dumps      : {traces['flight_dumps']}")
    nf = report.get("nonfinite")
    if nf:
        lines.append("  non-finite watchdog:")
        for e in nf.get("events", []):
            lines.append(
                f"    NONFINITE at step {e.get('step')}: first bad leaf "
                f"{e.get('leaf')!r} = {e.get('value')}"
                + (f" (loss scale {e.get('loss_scale')})" if e.get("loss_scale") is not None else "")
            )
        if not nf.get("events"):
            lines.append("    all probes finite")
        ls = nf.get("loss_scale")
        if ls:
            lines.append(
                f"    loss scale        : {ls.get('current')} "
                f"(min {ls.get('min')}, max {ls.get('max')}, {ls.get('backoffs')} backoffs)"
            )
    if len(lines) == 1:
        lines.append("  (no step/hbm/serving records found)")
    return "\n".join(lines)
