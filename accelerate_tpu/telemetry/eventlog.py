"""Structured, rank-tagged JSONL event log — the one sink every runtime
telemetry signal writes to.

Three record kinds share one schema (``SCHEMA_VERSION``), so a single
``accelerate-tpu telemetry summarize run.jsonl`` pass can explain a whole
run — training steps, recompiles, HBM samples, and serving counters
interleave in the same file:

* ``span``    — a timed region: ``{"kind": "span", "name": ..., "dur_ms": ...}``
  plus whatever fields the emitter attaches (a train step attaches its
  data-wait / dispatch / execute split);
* ``counter`` — a sampled value: ``{"kind": "counter", "name": ..., "value": ...}``;
* ``event``   — a point occurrence with a severity (``info`` / ``warning`` /
  ``error``): recompile detections, HBM-drift findings, prepare() markers.

Every record carries ``ts`` (unix seconds), ``rank`` (the jax process
index), and ``v`` (schema version). Writes are line-buffered in memory and
flushed every ``buffer_lines`` records (and at close/atexit) — one
``write()`` syscall per flush, so per-step overhead is a dict + a string
append. By default only the main process writes (``main_process_only``),
matching ``Accelerator.log``'s gating; worker ranks construct the log for
free and every emit is a no-op there.

jax is never imported at module load; the rank is resolved lazily and only
if a ``PartialState`` singleton already exists (telemetry must not be the
thing that initialises the backend).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import time
from typing import Optional

SCHEMA_VERSION = 1

#: record kinds a well-formed telemetry line may carry
KINDS = ("span", "counter", "event")

#: per-PROCESS monotonic sequence counter, shared by every EventLog
#: instance: after a crash, the flight-recorder dump and the main JSONL
#: merge into one deterministic order by sorting on ``seq`` (wall-clock
#: ``ts`` ties under coarse clocks; readers stay tolerant of old logs
#: that predate the field).
_SEQ = itertools.count()


def _resolve_rank() -> int:
    """The jax process index, WITHOUT initialising the backend: use the
    PartialState singleton if some other code already created it, else 0
    (single-process is the overwhelmingly common case on a dev box)."""
    try:
        from ..state import PartialState

        shared = PartialState._shared_state
        if shared and "process_index_host" in shared:
            return int(shared["process_index_host"])
    except Exception:
        pass
    return 0


class EventLog:
    """Buffered JSONL writer for telemetry records.

    ``path=None`` (or a non-main rank under ``main_process_only``)
    disables writing entirely — emits become no-ops — so instrumented
    code never needs an ``if telemetry:`` guard. ``clock`` is injectable
    for deterministic tests.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        rank: Optional[int] = None,
        main_process_only: bool = True,
        buffer_lines: int = 64,
        clock=time.time,
    ):
        self.path = path
        self.rank = _resolve_rank() if rank is None else int(rank)
        self._clock = clock
        self._buffer_lines = max(1, int(buffer_lines))
        self.enabled = path is not None and not (main_process_only and self.rank != 0)
        self._buf: list[str] = []
        self._taps: list = []
        self._closed = False
        self._atexit_registered = False
        if self.enabled:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            # truncate: one file == one run (summarize assumes it)
            with open(path, "w"):
                pass
            atexit.register(self.close)
            self._atexit_registered = True

    # ------------------------------------------------------------------ #
    # emit surface
    # ------------------------------------------------------------------ #

    def emit(self, kind: str, name: str, **fields) -> dict:
        """Append one record; returns the dict (written or not) so callers
        can reuse it for in-memory summaries."""
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        rec = {
            "v": SCHEMA_VERSION,
            "seq": next(_SEQ),
            "ts": self._clock(),
            "rank": self.rank,
            "kind": kind,
            "name": name,
        }
        rec.update(fields)
        if self.enabled and not self._closed:
            self._buf.append(json.dumps(rec, default=_json_default))
            if len(self._buf) >= self._buffer_lines:
                self.flush()
        # taps see every record, even on a disabled (path=None) log — the
        # flight recorder must keep recording when no JSONL is attached.
        for tap in self._taps:
            tap(rec)
        return rec

    def add_tap(self, fn) -> None:
        """Register ``fn(record_dict)`` to observe every emitted record
        (e.g. a per-replica :class:`~.flightrec.FlightRecorder`). Taps run
        inline on the emitting thread and must never raise or block."""
        if fn not in self._taps:
            self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        if fn in self._taps:
            self._taps.remove(fn)

    def counter(self, name: str, value, **fields) -> dict:
        return self.emit("counter", name, value=value, **fields)

    def event(self, name: str, severity: str = "info", **fields) -> dict:
        return self.emit("event", name, severity=severity, **fields)

    def span(self, name: str, **fields) -> "_Span":
        """``with log.span("prefill"):`` — emits the span with ``dur_ms``
        on exit. Extra ``fields`` ride along on the record."""
        return _Span(self, name, fields)

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    def flush(self):
        if not self._buf:
            return
        lines, self._buf = self._buf, []
        with open(self.path, "a") as f:
            f.write("\n".join(lines) + "\n")

    def close(self):
        if self._closed:
            return
        if self.enabled:
            self.flush()
        self._closed = True
        if self._atexit_registered:
            try:
                atexit.unregister(self.close)
            except Exception:
                pass
            self._atexit_registered = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Span:
    def __init__(self, log: EventLog, name: str, fields: dict):
        self._log = log
        self._name = name
        self._fields = fields

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        self._log.emit("span", self._name, dur_ms=round(dur_ms, 3), **self._fields)


def _json_default(obj):
    """Last-resort coercion: numpy/jax scalars -> python numbers, arrays ->
    their shape/dtype string (a telemetry line must never hold a tensor)."""
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                break
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    return repr(obj)


def merge_events(*event_lists: list[dict], source_ids=None) -> list[dict]:
    """Merge several record streams (the main JSONL + flight-recorder
    dumps, or one eventlog per worker PROCESS of a supervisor run) into
    one deterministic order.

    ``seq`` is a per-process counter: two workers' records can carry the
    same ``(ts, seq)`` with coarse clocks, so ties break by worker id
    first — the per-list ``source_ids`` entry when given (e.g. the
    worker name the filename carries), else the record's own ``rank``
    (workers log with ``rank=<slot>``), else the list position. Within
    one source, ``seq`` is total and authoritative. Stable, so true ties
    keep input order."""
    tagged = []
    for li, lst in enumerate(event_lists):
        sid = str(source_ids[li]) if source_ids is not None else None
        for rec in lst:
            src = sid if sid is not None else str(rec.get("rank", li))
            tagged.append(((rec.get("ts", 0.0), src, rec.get("seq", -1)), rec))
    tagged.sort(key=lambda kr: kr[0])
    return [rec for _, rec in tagged]


def read_events(path: str) -> list[dict]:
    """Parse a telemetry JSONL file, skipping blank/corrupt lines (a run
    killed mid-write must still summarize)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return events
