"""MFU / goodput accounting and live-HBM sampling.

MFU here is the standard definition: achieved model FLOP/s divided by the
chip generation's peak (``analysis.costmodel.PEAK_FLOPS_TABLE`` — the same
table the static cost model prices against, so static predictions and
runtime measurements can never disagree about what "peak" means). The
model FLOPs per step come from whichever source the caller has:

* an analytic count (``6 * params * tokens`` — what ``bench.py`` uses);
* ``flops_from_compiled(step._jitted...)`` when XLA's
  ``compiled.cost_analysis()`` is available (exact, includes attention);

The HBM sampler reads ``device.memory_stats()`` (present on TPU backends,
``None`` on CPU — sampling then degrades to a no-op) and cross-checks the
observed peak against the **static** flight-check estimate: when the two
disagree by more than ``drift_threshold`` (default 20%) it emits a
``hbm_drift`` warning event — either the static model is missing a buffer
(fix the liveness walk) or the program is materialising something the
author didn't intend (fix the program).
"""

from __future__ import annotations

from typing import Optional

from ..analysis.costmodel import HBM_GB_TABLE, PEAK_FLOPS_TABLE, device_generation, peak_flops
from .eventlog import EventLog

__all__ = [
    "PEAK_FLOPS_TABLE",
    "HBM_GB_TABLE",
    "device_generation",
    "peak_flops",
    "mfu",
    "goodput",
    "flops_from_compiled",
    "HBMSampler",
]


def mfu(
    flops_per_step: float,
    step_time_s: float,
    n_devices: int = 1,
    *,
    generation: Optional[str] = None,
    dtype: str = "bf16",
    peak: Optional[float] = None,
) -> float:
    """Model FLOPs utilisation in [0, ~1]. ``peak`` (FLOP/s per device)
    overrides the generation table; otherwise ``generation`` (or the
    attached device's kind) picks the table row."""
    if step_time_s <= 0:
        raise ValueError(f"step_time_s must be > 0, got {step_time_s}")
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if peak is None:
        peak = peak_flops(generation or device_generation() or "v5e", dtype)
    return flops_per_step / step_time_s / (peak * n_devices)


def goodput(records: list[dict]) -> Optional[float]:
    """Fraction of wall time spent dispatching+executing (vs waiting for
    data) over a list of :class:`StepTelemetry` records."""
    total = sum(r.get("dur_ms", 0.0) for r in records)
    if total <= 0:
        return None
    busy = sum(r.get("dispatch_ms", 0.0) + r.get("execute_ms", 0.0) for r in records)
    return min(1.0, busy / total)


def flops_from_compiled(compiled) -> Optional[float]:
    """Per-call FLOPs from an XLA compiled executable's
    ``cost_analysis()``, or None when the backend doesn't report it.
    Accepts a ``jax.jit`` wrapper (uses its first cached executable), a
    lowered+compiled object, or anything exposing ``cost_analysis``."""
    ca = getattr(compiled, "cost_analysis", None)
    if ca is None:
        return None
    try:
        analysis = ca()
    except Exception:
        return None
    # jax versions differ: a dict, or a list with one dict per device
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = analysis.get("flops")
    return float(flops) if flops else None


def _default_stats():
    """Max live/peak bytes over local devices from ``memory_stats()``;
    None on backends (CPU) that don't report."""
    import jax

    best = None
    for d in jax.local_devices():
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if not s:
            continue
        cur = {
            "bytes_in_use": int(s.get("bytes_in_use") or 0),
            "peak_bytes_in_use": int(s.get("peak_bytes_in_use") or 0),
            "bytes_limit": int(s.get("bytes_limit") or 0),
        }
        if best is None or cur["peak_bytes_in_use"] > best["peak_bytes_in_use"]:
            best = cur
    return best


class HBMSampler:
    """Periodic live-memory sampler + static-vs-observed drift check.

    ``static_peak_bytes`` is flight-check's per-device estimate
    (``FlightReport.peak_hbm_bytes``); when given, it is logged once as an
    ``hbm_static_estimate`` event and every :meth:`sample` cross-checks the
    observed peak against it, emitting ONE ``hbm_drift`` warning the first
    time relative disagreement exceeds ``drift_threshold``. ``stats_fn``
    is injectable for tests (and for backends with no ``memory_stats``).
    """

    def __init__(
        self,
        log: Optional[EventLog] = None,
        *,
        static_peak_bytes: Optional[int] = None,
        drift_threshold: float = 0.2,
        stats_fn=None,
    ):
        self.log = log if log is not None else EventLog(None)
        self.static_peak_bytes = static_peak_bytes
        self.drift_threshold = drift_threshold
        self._stats_fn = stats_fn or _default_stats
        self.observed_peak_bytes = 0
        self.samples = 0
        self.drift_event: Optional[dict] = None
        if static_peak_bytes is not None:
            self.log.event("hbm_static_estimate", bytes=int(static_peak_bytes))

    def sample(self) -> Optional[dict]:
        """Read live memory; returns the stats dict (or None when the
        backend reports nothing)."""
        stats = self._stats_fn()
        if stats is None:
            return None
        self.samples += 1
        self.observed_peak_bytes = max(self.observed_peak_bytes, stats["peak_bytes_in_use"])
        self.log.counter("hbm_bytes_in_use", stats["bytes_in_use"])
        self.log.counter(
            "hbm_peak_bytes",
            self.observed_peak_bytes,
            bytes_limit=stats.get("bytes_limit"),
        )
        self._check_drift()
        return stats

    def _check_drift(self):
        if (
            self.drift_event is not None
            or not self.static_peak_bytes
            or not self.observed_peak_bytes
        ):
            return
        rel = abs(self.observed_peak_bytes - self.static_peak_bytes) / self.static_peak_bytes
        if rel > self.drift_threshold:
            self.drift_event = self.log.event(
                "hbm_drift",
                severity="warning",
                observed_peak_bytes=self.observed_peak_bytes,
                static_peak_bytes=int(self.static_peak_bytes),
                rel_error=round(rel, 4),
                threshold=self.drift_threshold,
            )

    def headroom_bytes(self, hbm_gb: Optional[float] = None) -> Optional[int]:
        """Bytes between the observed peak and the device HBM capacity
        (table lookup by attached generation when ``hbm_gb`` is omitted)."""
        if hbm_gb is None:
            gen = device_generation()
            if gen is None:
                return None
            hbm_gb = HBM_GB_TABLE[gen]
        if not self.observed_peak_bytes:
            return None
        return int(hbm_gb * 1024**3) - self.observed_peak_bytes
