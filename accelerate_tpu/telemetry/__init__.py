"""Runtime observability: step timeline, recompile watchdog, MFU/goodput,
HBM sampling, serving metrics — one JSONL event stream, one summarize CLI.

The static tier (``accelerate_tpu.analysis``: lint, flight-check, cost
model) predicts what a step *should* do; this package measures what it
*actually* does and cross-checks the two (observed peak HBM vs the
flight-check estimate, MFU against the same per-generation peak-FLOPs
table the cost model prices with). Quick start::

    from accelerate_tpu.telemetry import Telemetry

    tel = Telemetry("run.jsonl")
    step = tel.wrap(step)             # instruments every call
    for batch in loader:
        loss = step(batch)
    tel.close()
    # then: accelerate-tpu telemetry summarize run.jsonl

or, through the Accelerator (the usual path — see
``docs/usage_guides/telemetry.md``)::

    accelerator = Accelerator(kwargs_handlers=[TelemetryKwargs(...)])
    step = accelerator.telemetry.wrap(accelerator.build_train_step(loss_fn))
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from .critpath import CritPathMonitor, decompose, render_critpath
from .eventlog import SCHEMA_VERSION, EventLog, merge_events, read_events
from .flightrec import FlightRecorder, read_dump, render_dump
from .httpd import TelemetryHTTPD
from .mfu import (
    HBM_GB_TABLE,
    PEAK_FLOPS_TABLE,
    HBMSampler,
    device_generation,
    flops_from_compiled,
    goodput,
    mfu,
    peak_flops,
)
from .nonfinite import NonFiniteWatchdog
from .serving_metrics import ServingMetrics
from .step import StepTelemetry, diff_signatures, signature_of
from .summarize import render_text, summarize, summarize_file
from .trace import TraceConfig, Tracer, chrome_trace, traces_from_events
from .wire import hlo_collective_sites, hlo_wire_bytes, wire_dtype_upcast

__all__ = [
    "SCHEMA_VERSION",
    "EventLog",
    "read_events",
    "merge_events",
    "Tracer",
    "TraceConfig",
    "traces_from_events",
    "chrome_trace",
    "FlightRecorder",
    "read_dump",
    "render_dump",
    "CritPathMonitor",
    "decompose",
    "render_critpath",
    "TelemetryHTTPD",
    "StepTelemetry",
    "signature_of",
    "diff_signatures",
    "HBMSampler",
    "NonFiniteWatchdog",
    "ServingMetrics",
    "Telemetry",
    "hlo_collective_sites",
    "hlo_wire_bytes",
    "wire_dtype_upcast",
    "PEAK_FLOPS_TABLE",
    "HBM_GB_TABLE",
    "device_generation",
    "peak_flops",
    "mfu",
    "goodput",
    "flops_from_compiled",
    "summarize",
    "summarize_file",
    "render_text",
]


class Telemetry:
    """Facade bundling one :class:`EventLog`, one :class:`StepTelemetry`,
    and one :class:`HBMSampler` for a run — what ``Accelerator.telemetry``
    hands out.

    ``hbm_sample_every=N`` samples live memory every N wrapped steps;
    ``forward_fn`` + ``forward_every=N`` push a rolling summary dict to a
    callback every N steps (the Accelerator wires ``Accelerator.log`` in
    here, so step time / MFU / recompile counts land in the active
    trackers automatically). ``static_hbm_bytes`` seeds the drift check
    with a flight-check prediction. ``nonfinite_every=N`` opts in to the
    :class:`NonFiniteWatchdog` finiteness probe (0 = off — each probe is
    a host sync).
    """

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        rank: Optional[int] = None,
        main_process_only: bool = True,
        warmup_steps: int = 2,
        fence: bool = True,
        watchdog: bool = True,
        flops_per_step: Optional[float] = None,
        peak_flops_per_device: Optional[float] = None,
        n_devices: int = 1,
        hbm_sample_every: int = 10,
        static_hbm_bytes: Optional[int] = None,
        hbm_drift_threshold: float = 0.2,
        forward_fn: Optional[Callable[[dict, Optional[int]], None]] = None,
        forward_every: int = 0,
        nonfinite_every: int = 0,
    ):
        self.log = EventLog(path, rank=rank, main_process_only=main_process_only)
        self.steps = StepTelemetry(
            self.log,
            warmup_steps=warmup_steps,
            fence=fence,
            watchdog=watchdog,
            flops_per_step=flops_per_step,
            peak_flops_per_device=peak_flops_per_device,
            n_devices=n_devices,
        )
        self.hbm = HBMSampler(
            self.log, static_peak_bytes=static_hbm_bytes, drift_threshold=hbm_drift_threshold
        )
        self.nonfinite = NonFiniteWatchdog(self.log, every=nonfinite_every)
        self._hbm_sample_every = max(0, int(hbm_sample_every))
        self._forward_fn = forward_fn
        self._forward_every = max(0, int(forward_every))
        self.steps.on_step = self._on_step

    # -- delegation ----------------------------------------------------- #

    @property
    def path(self) -> Optional[str]:
        return self.log.path

    @property
    def recompiles(self) -> int:
        return self.steps.recompiles

    def wrap(self, step_fn: Callable, **kwargs) -> Callable:
        return self.steps.wrap(step_fn, **kwargs)

    def step(self, batch=None, **kwargs):
        return self.steps.step(batch, **kwargs)

    def event(self, name: str, **fields) -> dict:
        return self.log.event(name, **fields)

    def record_wire_bytes(
        self,
        predicted_bytes: int,
        measured_bytes: int,
        *,
        label: str = "step",
        drift_threshold: float = 0.1,
        by_primitive: Optional[dict] = None,
        requested_wire_dtype: Optional[str] = None,
        sites: Optional[list] = None,
        platform: Optional[str] = None,
    ) -> dict:
        """Record one wire-byte counter pair: the cost-model prediction
        vs the compiled-HLO measurement (:func:`~accelerate_tpu.telemetry.
        hlo_wire_bytes`). Lands as a ``wire_bytes`` event on the run
        timeline (with a ``severity=warning`` twin when the two disagree
        by more than ``drift_threshold`` — the byte analogue of
        ``perf_model_drift``) and accumulates in :attr:`wire_counters`
        for ``summary()``.

        With ``requested_wire_dtype`` (a ``grad_compression`` scheme:
        ``"bf16"|"int8"|"fp8"``) and the measurement's ``sites`` list,
        a ONE-TIME ``wire_dtype_upcast`` warning event fires when the
        compiled program's dominant collective moves a wider dtype than
        requested — naming the platform, because this is a backend
        lowering property (XLA:CPU upcasts bf16 collectives to f32; TPU
        backends keep the narrow wire), so the compression saving being
        absent here does NOT mean it is absent on TPU."""
        predicted_bytes, measured_bytes = int(predicted_bytes), int(measured_bytes)
        drift = (
            abs(measured_bytes - predicted_bytes) / predicted_bytes
            if predicted_bytes
            else (1.0 if measured_bytes else 0.0)
        )
        rec = {
            "label": label,
            "predicted_bytes": predicted_bytes,
            "measured_bytes": measured_bytes,
            "drift": round(drift, 4),
        }
        if by_primitive:
            rec["by_primitive"] = {k: int(v) for k, v in by_primitive.items()}
        if not hasattr(self, "wire_counters"):
            self.wire_counters: list[dict] = []
        self.wire_counters.append(rec)
        self.log.event(
            "wire_bytes",
            severity="warning" if drift > drift_threshold else "info",
            **rec,
        )
        if requested_wire_dtype is not None and sites:
            from .wire import wire_dtype_upcast

            up = wire_dtype_upcast(sites, requested_wire_dtype)
            if up is not None and requested_wire_dtype not in getattr(self, "_upcast_warned", set()):
                if not hasattr(self, "_upcast_warned"):
                    self._upcast_warned: set = set()
                self._upcast_warned.add(requested_wire_dtype)
                if platform is None:
                    import sys

                    jax = sys.modules.get("jax")
                    platform = jax.default_backend() if jax is not None else "unknown"
                self.log.event(
                    "wire_dtype_upcast",
                    severity="warning",
                    label=label,
                    platform=platform,
                    message=(
                        f"requested a {up['requested']} wire but the compiled program's "
                        f"dominant collective moves {up['measured_dtype']} on the "
                        f"{platform} backend — the compression saving is backend-gated "
                        "(TPU backends keep the narrow dtype on the wire)"
                    ),
                    **up,
                )
                rec["dtype_upcast"] = up
        return rec

    def set_static_hbm_estimate(self, peak_bytes: int):
        """Attach a flight-check peak-HBM prediction after construction
        (``Accelerator.flight_check`` calls this when telemetry is live)."""
        self.hbm.static_peak_bytes = int(peak_bytes)
        self.log.event("hbm_static_estimate", bytes=int(peak_bytes))

    def set_static_step_estimate(self, predicted_ms: float, *, threshold=None):
        """Attach a perf-check step-time prediction after construction
        (``Accelerator.perf_check`` calls this when telemetry is live);
        arms the one-shot ``perf_model_drift`` cross-check in
        :class:`StepTelemetry`."""
        self.steps.set_static_step_estimate(predicted_ms, threshold=threshold)

    def summary(self) -> dict:
        out = self.steps.summary()
        if self.hbm.observed_peak_bytes:
            out["observed_peak_hbm_bytes"] = self.hbm.observed_peak_bytes
        if self.hbm.static_peak_bytes:
            out["static_peak_hbm_bytes"] = int(self.hbm.static_peak_bytes)
        if self.nonfinite.enabled or self.nonfinite.probes:
            out["nonfinite"] = self.nonfinite.summary()
        if getattr(self, "wire_counters", None):
            out["wire_bytes"] = list(self.wire_counters)
        return out

    def flush(self):
        self.log.flush()

    def close(self):
        self.log.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- per-step plumbing ---------------------------------------------- #

    def _on_step(self, rec: dict):
        i = rec["step"]
        if self._hbm_sample_every and i % self._hbm_sample_every == 0:
            self.hbm.sample()
        if self._forward_fn is not None and self._forward_every and i > 0 and i % self._forward_every == 0:
            recent = [r for r in list(self.steps.records)[-self._forward_every:] if not r["compile"]]
            values = {
                "telemetry/step_ms": round(
                    sum(r["dur_ms"] for r in recent) / len(recent), 3
                ) if recent else None,
                "telemetry/data_wait_ms": round(
                    sum(r["data_wait_ms"] for r in recent) / len(recent), 3
                ) if recent else None,
                "telemetry/recompiles": self.steps.recompiles,
            }
            mfus = [r["mfu"] for r in recent if "mfu" in r]
            if mfus:
                values["telemetry/mfu"] = round(sum(mfus) / len(mfus), 5)
            if self.hbm.observed_peak_bytes:
                values["telemetry/peak_hbm_bytes"] = self.hbm.observed_peak_bytes
            self._forward_fn({k: v for k, v in values.items() if v is not None}, i)


def default_path(logging_dir: Optional[str] = None) -> str:
    """Default event-log location: ``{logging_dir}/telemetry.jsonl``.
    With no logging/project dir configured the log lands under
    ``runs/`` (created on first write, and gitignored) instead of the
    working directory — a bare ``Accelerator`` in a repo checkout must
    not litter the tree with run logs."""
    return os.path.join(logging_dir or "runs", "telemetry.jsonl")
