"""The fleet's first real port: a stdlib threaded HTTP endpoint for
``/metrics``, ``/healthz``, and ``/traces``.

ROADMAP item 1 ("leave the process") needs the Prometheus exposition on
an actual socket instead of a method you must already be in-process to
call. This is that piece, deliberately tiny: ``ThreadingHTTPServer``
from the stdlib, one daemon accept thread, handlers that *read*
injected callables and format outside any lock.

* ``GET /metrics``  — byte-identical output of
  :func:`~accelerate_tpu.telemetry.serving_metrics.fleet_prometheus_text`
  (``text/plain; version=0.0.4``);
* ``GET /healthz``  — ``FleetRouter.health()`` as JSON; 200 while any
  replica still serves, 503 once fleet capacity is lost. Behind
  :meth:`TelemetryHTTPD.for_supervisor` the rows are REAL worker
  processes (``ProcessSupervisor.health()``), so 503 means zero live
  workers, not zero in-process objects;
* ``GET /traces``   — recent completed traces (``?n=`` caps the count).

With a request surface attached (:meth:`TelemetryHTTPD.for_supervisor`),
the front door also serves inference:

* ``POST /v1/generate``        — body ``{"prompt": [ids], "max_new_tokens",
  "stop_sequences", "priority", "stream"}``; the ``X-Priority`` header (an
  integer scheduler class, lower admits sooner — PR-10 semantics) or the
  ``X-SLO-Class`` alias (``interactive``/``standard``/``batch``) overrides
  the body priority. Non-streaming replies one JSON document when the
  request finishes; ``"stream": true`` (or ``Accept: text/event-stream``)
  switches to SSE: one ``event: token`` per new token (``data`` is
  ``{"i", "token", "lp"}``), then a terminal ``event: done`` /
  ``event: error``. Client disconnect mid-stream cancels the request on
  the fleet. 429 when the fleet sheds, 503 when zero workers serve.
* ``GET /v1/requests/<id>``    — request state snapshot (JSON);
* ``DELETE /v1/generate/<id>`` — cancel; replies the tokens so far.

Host-concurrency discipline (strict ``fleet-check``, TPU901-903): the
accept loop runs in a module-level function that receives the server
object as an argument — no shared mutable attribute crosses thread
contexts, so there is nothing a lock would need to guard; ``stop()``
shuts the server down and joins the (daemon) thread from the caller's
context with no lock held.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

#: health states that count as "still serving" for the 503 decision —
#: mirrors ``Replica.is_serving`` in serving_fleet.py (and
#: ``SERVING_WORKER_STATES`` in serving_proc.py for real processes).
_SERVING_STATES = ("healthy", "degraded")

#: ``X-SLO-Class`` header → PR-10 integer scheduler class
SLO_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}

#: terminal request states (the stream/poll loop stops on these)
_TERMINAL_STATES = ("done", "cancelled", "lost", "shed")


def _serve(srv: ThreadingHTTPServer) -> None:
    """Accept-loop thread body. Takes the server as an argument so the
    thread shares no mutable attribute with the owning object."""
    srv.serve_forever(poll_interval=0.05)


class _Handler(BaseHTTPRequestHandler):
    """Routes the three endpoints; all state comes from ``server.app``,
    a dict of callables frozen before the accept thread starts."""

    server_version = "accelerate-tpu-telemetry/1"

    def do_GET(self):  # noqa: N802 - stdlib handler contract
        app = self.server.app
        parsed = urlparse(self.path)
        route = parsed.path.rstrip("/") or "/"
        if route == "/metrics":
            body = app["metrics"]().encode("utf-8")
            self._reply(200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif route == "/healthz":
            health = app["health"]()
            serving = any(row.get("health") in _SERVING_STATES for row in health.values())
            body = json.dumps({"serving": serving, "replicas": health}, sort_keys=True).encode("utf-8")
            self._reply(200 if serving else 503, body, "application/json")
        elif route == "/traces":
            qs = parse_qs(parsed.query)
            try:
                n = int(qs.get("n", ["64"])[0])
            except ValueError:
                n = 64
            body = json.dumps({"traces": app["traces"](max(0, n))}, default=repr).encode("utf-8")
            self._reply(200, body, "application/json")
        elif route.startswith("/v1/requests/") and app.get("stream") is not None:
            rid = self._request_id(route)
            if rid is None:
                self._reply(400, b'{"error": "bad request id"}\n', "application/json")
                return
            try:
                state = app["stream"](rid)
            except KeyError:
                self._reply(404, b'{"error": "unknown request"}\n', "application/json")
                return
            body = json.dumps({"id": rid, **state}, sort_keys=True).encode("utf-8")
            self._reply(200, body, "application/json")
        else:
            self._reply(404, b'{"error": "unknown path"}\n', "application/json")

    # ------------------------------------------------------------------ #
    # inference front door (only routed when a submit surface is wired)
    # ------------------------------------------------------------------ #

    def do_POST(self):  # noqa: N802 - stdlib handler contract
        app = self.server.app
        route = urlparse(self.path).path.rstrip("/")
        if route != "/v1/generate" or app.get("submit") is None:
            self._reply(404, b'{"error": "unknown path"}\n', "application/json")
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n).decode("utf-8") or "{}")
            prompt = [int(t) for t in body["prompt"]]
        except (KeyError, TypeError, ValueError, UnicodeDecodeError, json.JSONDecodeError):
            self._reply(
                400,
                b'{"error": "body must be JSON with an integer \\"prompt\\" list"}\n',
                "application/json",
            )
            return
        stream = bool(body.get("stream")) or "text/event-stream" in (
            self.headers.get("Accept") or ""
        )
        try:
            rid = app["submit"](
                {
                    "prompt": prompt,
                    "max_new_tokens": int(body.get("max_new_tokens", 16)),
                    "stop_sequences": body.get("stop_sequences") or [],
                    "priority": self._priority(body),
                }
            )
        except Exception as e:  # noqa: BLE001 - mapped to a structured status
            msg = str(e)
            status = 429 if ("shed" in msg or "draining" in msg) else 503
            self._reply(
                status, json.dumps({"error": msg}).encode("utf-8"), "application/json"
            )
            return
        if stream:
            self._stream_sse(app, rid)
        else:
            self._wait_json(app, rid, timeout=float(body.get("timeout_s", 120.0)))

    def do_DELETE(self):  # noqa: N802 - stdlib handler contract
        app = self.server.app
        route = urlparse(self.path).path.rstrip("/")
        if not route.startswith("/v1/generate/") or app.get("cancel") is None:
            self._reply(404, b'{"error": "unknown path"}\n', "application/json")
            return
        rid = self._request_id(route)
        if rid is None:
            self._reply(400, b'{"error": "bad request id"}\n', "application/json")
            return
        try:
            tokens = app["cancel"](rid)
        except KeyError:
            self._reply(404, b'{"error": "unknown request"}\n', "application/json")
            return
        body = json.dumps({"id": rid, "cancelled": True, "tokens": list(tokens)})
        self._reply(200, body.encode("utf-8"), "application/json")

    def _priority(self, body: dict) -> int:
        """Body priority, overridden by the ``X-SLO-Class`` name or an
        explicit integer ``X-Priority`` header (which wins)."""
        priority = int(body.get("priority", 0))
        slo = self.headers.get("X-SLO-Class")
        if slo:
            priority = SLO_CLASSES.get(slo.strip().lower(), priority)
        xp = self.headers.get("X-Priority")
        if xp is not None:
            try:
                priority = int(xp)
            except ValueError:
                pass  # keep the SLO/body priority; a bad header is not fatal
        return priority

    def _stream_sse(self, app: dict, rid: int) -> None:
        """Server-sent events until the request reaches a terminal state.
        A broken pipe (client went away) cancels the request on the fleet
        so no orphaned decode burns slots."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("X-Request-Id", str(rid))
        self.end_headers()
        sent = 0
        try:
            while True:
                try:
                    s = app["stream"](rid)
                except KeyError:
                    self._sse("error", {"id": rid, "state": "unknown"})
                    return
                toks, lps = s.get("tokens") or [], s.get("lps") or []
                while sent < len(toks):
                    self._sse(
                        "token",
                        {
                            "id": rid,
                            "i": sent,
                            "token": toks[sent],
                            "lp": lps[sent] if sent < len(lps) else None,
                        },
                    )
                    sent += 1
                if s.get("state") in _TERMINAL_STATES:
                    if s["state"] in ("done", "cancelled"):
                        self._sse(
                            "done",
                            {
                                "id": rid,
                                "state": s["state"],
                                "tokens": toks,
                                "final": s.get("final"),
                                "lps": lps,
                            },
                        )
                    else:
                        self._sse(
                            "error",
                            {
                                "id": rid,
                                "state": s["state"],
                                "reason": s.get("lost_reason"),
                            },
                        )
                    return
                time.sleep(0.01)
        except (BrokenPipeError, ConnectionResetError, OSError):
            cancel = app.get("cancel")
            if cancel is not None:
                try:
                    cancel(rid)
                except (KeyError, RuntimeError):
                    # already finished or already gone — nothing to free
                    return

    def _wait_json(self, app: dict, rid: int, timeout: float) -> None:
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            try:
                s = app["stream"](rid)
            except KeyError:
                self._reply(404, b'{"error": "unknown request"}\n', "application/json")
                return
            if s.get("state") in _TERMINAL_STATES:
                break
            if time.monotonic() > deadline:
                body = json.dumps({"id": rid, "error": "timeout", "state": s.get("state")})
                self._reply(504, body.encode("utf-8"), "application/json")
                return
            time.sleep(0.01)
        if s["state"] in ("done", "cancelled"):
            body = json.dumps(
                {
                    "id": rid,
                    "state": s["state"],
                    "tokens": s.get("tokens") or [],
                    "final": s.get("final"),
                    "lps": s.get("lps") or [],
                },
                sort_keys=True,
            )
            self._reply(200, body.encode("utf-8"), "application/json")
        else:
            body = json.dumps(
                {"id": rid, "state": s["state"], "error": s.get("lost_reason") or s["state"]}
            )
            self._reply(500, body.encode("utf-8"), "application/json")

    def _sse(self, event: str, data: dict) -> None:
        chunk = f"event: {event}\ndata: {json.dumps(data)}\n\n"
        self.wfile.write(chunk.encode("utf-8"))
        self.wfile.flush()

    @staticmethod
    def _request_id(route: str) -> Optional[int]:
        try:
            return int(route.rsplit("/", 1)[1])
        except (IndexError, ValueError):
            return None

    def _reply(self, status: int, body: bytes, ctype: str):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class TelemetryHTTPD:
    """Owns one ``ThreadingHTTPServer`` + its daemon accept thread.

    ``port=0`` binds an ephemeral port (tests); :meth:`start` returns
    the bound port. Usable as a context manager."""

    def __init__(
        self,
        *,
        metrics_fn: Callable[[], str],
        health_fn: Optional[Callable[[], dict]] = None,
        traces_fn: Optional[Callable[[int], list]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.host = host
        self.port = port
        self._app = {
            "metrics": metrics_fn,
            "health": health_fn if health_fn is not None else dict,
            "traces": traces_fn if traces_fn is not None else (lambda n: []),
            # inference surface: wired by for_supervisor(); None keeps the
            # /v1/* routes 404 on a pure-telemetry endpoint
            "submit": None,
            "cancel": None,
            "stream": None,
        }
        self._srv: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def for_router(cls, router, *, host: str = "127.0.0.1", port: int = 0) -> "TelemetryHTTPD":
        """Wire the three endpoints to a ``FleetRouter``: ``/metrics`` is
        ``router.prometheus_text`` verbatim, ``/healthz`` is
        ``router.health()``, ``/traces`` drains the router's tracer."""

        def traces(n: int) -> list:
            tracer = getattr(router, "tracer", None)
            return tracer.completed(n) if tracer is not None else []

        return cls(
            metrics_fn=router.prometheus_text,
            health_fn=router.health,
            traces_fn=traces,
            host=host,
            port=port,
        )

    @classmethod
    def for_supervisor(cls, supervisor, *, host: str = "127.0.0.1", port: int = 0) -> "TelemetryHTTPD":
        """The multi-process front door: telemetry endpoints plus the
        ``/v1/*`` inference surface, all wired to a
        :class:`~accelerate_tpu.serving_proc.ProcessSupervisor`.
        ``/healthz`` reflects REAL worker-process liveness (503 on zero
        live workers); submit/cancel cross into the supervisor's pump
        thread through its command queue, and streams read its published
        snapshots — handler threads never touch a worker socket."""

        def submit(body: dict) -> int:
            return supervisor.submit(
                body["prompt"],
                max_new_tokens=body["max_new_tokens"],
                stop_sequences=body["stop_sequences"],
                priority=body["priority"],
                wait=True,
            )

        def traces(n: int) -> list:
            tracer = getattr(supervisor, "_tracer", None)
            return tracer.completed(n) if tracer is not None else []

        httpd = cls(
            metrics_fn=supervisor.prometheus_text,
            health_fn=supervisor.health,
            traces_fn=traces,
            host=host,
            port=port,
        )
        httpd._app["submit"] = submit
        httpd._app["cancel"] = supervisor.cancel
        httpd._app["stream"] = supervisor._stream
        return httpd

    # ------------------------------------------------------------------ #

    def start(self) -> int:
        """Bind (on the caller's thread, so the port is known before the
        accept thread exists) and start serving; returns the port."""
        if self._srv is not None:
            return self.port
        srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        srv.daemon_threads = True
        srv.app = self._app
        thread = threading.Thread(target=_serve, args=(srv,), name="telemetry-httpd", daemon=True)
        thread.start()
        self._srv = srv
        self._thread = thread
        self.port = srv.server_address[1]
        return self.port

    def stop(self) -> None:
        """Shut down the accept loop and join the thread (caller's
        context, no lock held)."""
        srv, thread = self._srv, self._thread
        self._srv = None
        self._thread = None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def __enter__(self) -> "TelemetryHTTPD":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
