"""The fleet's first real port: a stdlib threaded HTTP endpoint for
``/metrics``, ``/healthz``, and ``/traces``.

ROADMAP item 1 ("leave the process") needs the Prometheus exposition on
an actual socket instead of a method you must already be in-process to
call. This is that piece, deliberately tiny: ``ThreadingHTTPServer``
from the stdlib, one daemon accept thread, handlers that *read*
injected callables and format outside any lock.

* ``GET /metrics``  — byte-identical output of
  :func:`~accelerate_tpu.telemetry.serving_metrics.fleet_prometheus_text`
  (``text/plain; version=0.0.4``);
* ``GET /healthz``  — ``FleetRouter.health()`` as JSON; 200 while any
  replica still serves, 503 once fleet capacity is lost;
* ``GET /traces``   — recent completed traces (``?n=`` caps the count).

Host-concurrency discipline (strict ``fleet-check``, TPU901-903): the
accept loop runs in a module-level function that receives the server
object as an argument — no shared mutable attribute crosses thread
contexts, so there is nothing a lock would need to guard; ``stop()``
shuts the server down and joins the (daemon) thread from the caller's
context with no lock held.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

#: health states that count as "still serving" for the 503 decision —
#: mirrors ``Replica.is_serving`` in serving_fleet.py.
_SERVING_STATES = ("healthy", "degraded")


def _serve(srv: ThreadingHTTPServer) -> None:
    """Accept-loop thread body. Takes the server as an argument so the
    thread shares no mutable attribute with the owning object."""
    srv.serve_forever(poll_interval=0.05)


class _Handler(BaseHTTPRequestHandler):
    """Routes the three endpoints; all state comes from ``server.app``,
    a dict of callables frozen before the accept thread starts."""

    server_version = "accelerate-tpu-telemetry/1"

    def do_GET(self):  # noqa: N802 - stdlib handler contract
        app = self.server.app
        parsed = urlparse(self.path)
        route = parsed.path.rstrip("/") or "/"
        if route == "/metrics":
            body = app["metrics"]().encode("utf-8")
            self._reply(200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif route == "/healthz":
            health = app["health"]()
            serving = any(row.get("health") in _SERVING_STATES for row in health.values())
            body = json.dumps({"serving": serving, "replicas": health}, sort_keys=True).encode("utf-8")
            self._reply(200 if serving else 503, body, "application/json")
        elif route == "/traces":
            qs = parse_qs(parsed.query)
            try:
                n = int(qs.get("n", ["64"])[0])
            except ValueError:
                n = 64
            body = json.dumps({"traces": app["traces"](max(0, n))}, default=repr).encode("utf-8")
            self._reply(200, body, "application/json")
        else:
            self._reply(404, b'{"error": "unknown path"}\n', "application/json")

    def _reply(self, status: int, body: bytes, ctype: str):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class TelemetryHTTPD:
    """Owns one ``ThreadingHTTPServer`` + its daemon accept thread.

    ``port=0`` binds an ephemeral port (tests); :meth:`start` returns
    the bound port. Usable as a context manager."""

    def __init__(
        self,
        *,
        metrics_fn: Callable[[], str],
        health_fn: Optional[Callable[[], dict]] = None,
        traces_fn: Optional[Callable[[int], list]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.host = host
        self.port = port
        self._app = {
            "metrics": metrics_fn,
            "health": health_fn if health_fn is not None else dict,
            "traces": traces_fn if traces_fn is not None else (lambda n: []),
        }
        self._srv: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def for_router(cls, router, *, host: str = "127.0.0.1", port: int = 0) -> "TelemetryHTTPD":
        """Wire the three endpoints to a ``FleetRouter``: ``/metrics`` is
        ``router.prometheus_text`` verbatim, ``/healthz`` is
        ``router.health()``, ``/traces`` drains the router's tracer."""

        def traces(n: int) -> list:
            tracer = getattr(router, "tracer", None)
            return tracer.completed(n) if tracer is not None else []

        return cls(
            metrics_fn=router.prometheus_text,
            health_fn=router.health,
            traces_fn=traces,
            host=host,
            port=port,
        )

    # ------------------------------------------------------------------ #

    def start(self) -> int:
        """Bind (on the caller's thread, so the port is known before the
        accept thread exists) and start serving; returns the port."""
        if self._srv is not None:
            return self.port
        srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        srv.daemon_threads = True
        srv.app = self._app
        thread = threading.Thread(target=_serve, args=(srv,), name="telemetry-httpd", daemon=True)
        thread.start()
        self._srv = srv
        self._thread = thread
        self.port = srv.server_address[1]
        return self.port

    def stop(self) -> None:
        """Shut down the accept loop and join the thread (caller's
        context, no lock held)."""
        srv, thread = self._srv, self._thread
        self._srv = None
        self._thread = None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def __enter__(self) -> "TelemetryHTTPD":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
