"""Non-finite watchdog: the runtime counterpart of the static TPU602
overflow proof (``analysis.numerics``).

The numerics analyzer proves — under stated input assumptions — that a
program *cannot* overflow fp16/fp8; this watchdog catches the runs where
the assumptions break. Every ``every`` steps it probes the loss, the
gradient norm, and (optionally) a gradient pytree for NaN/inf, and
tracks the fp16 dynamic loss-scale trajectory. The first non-finite
value latches ONE ``nonfinite`` event naming the **first bad leaf** (the
same fire-once discipline as ``perf_model_drift`` and ``hbm_drift`` —
a diverged run floods every later step, and one event with the first
culprit is what you debug from). Loss-scale changes land as
``loss_scale`` events, so the backoff staircase that precedes an
overflow is visible in the same JSONL timeline.

Opt-in (a probe is a host sync): pass
``TelemetryKwargs(nonfinite_every=N)`` and the fast-path train step
probes automatically, or drive it by hand::

    wd = telemetry.nonfinite
    wd.observe(step, loss=loss, grad_norm=gnorm, loss_scale=scale)

``accelerate-tpu telemetry summarize`` renders the section: probes run,
the latched event, and the loss-scale min/max/backoff count.
"""

from __future__ import annotations

from typing import Any, Optional

from .eventlog import EventLog


def _tree_first_nonfinite(tree) -> Optional[str]:
    """Dotted path of the first non-finite leaf in a pytree, or None.
    Forces a device->host sync for each leaf checked — callers gate on
    the probe cadence.

    Device arrays (including ZeRO/FSDP-sharded gradient shards) are
    probed with an on-device ``isfinite`` reduction, so only the scalar
    verdict crosses to the host — probing a sharded leaf must never
    gather it (``np.asarray`` on a distributed array materialises the
    FULL array on one host, and fails outright for multi-process
    non-addressable shards)."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        if hasattr(leaf, "sharding") or hasattr(leaf, "device"):
            import jax.numpy as jnp

            try:
                if jnp.issubdtype(leaf.dtype, jnp.floating) or jnp.issubdtype(
                    leaf.dtype, jnp.complexfloating
                ):
                    if not bool(jnp.all(jnp.isfinite(leaf))):
                        return jax.tree_util.keystr(path)
                continue
            except TypeError:
                continue
        try:
            arr = np.asarray(leaf, dtype=np.float64)
        except (TypeError, ValueError):
            continue
        if not np.isfinite(arr).all():
            return jax.tree_util.keystr(path)
    return None


def _scalar(value) -> Optional[float]:
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


class NonFiniteWatchdog:
    """Every-N-steps finiteness probe on loss / grad-norm / gradients,
    plus the fp16 loss-scale trajectory. Fires ONE latched ``nonfinite``
    event naming the first bad leaf."""

    def __init__(self, log: Optional[EventLog] = None, *, every: int = 0, max_trajectory: int = 256):
        self.log = log if log is not None else EventLog(None)
        self.every = max(0, int(every))
        self.probes = 0
        self.nonfinite_event: Optional[dict] = None
        #: non-finite grads the fp16 scaler already handled (skipped step
        #: + backoff) — counted, never latched
        self.scaler_skips = 0
        #: (step, scale) pairs, recorded on change only
        self.scale_trajectory: list[tuple[int, float]] = []
        self.scale_backoffs = 0
        self._max_trajectory = max(2, int(max_trajectory))
        self._last_scale: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.every > 0

    def observe(
        self,
        step: int,
        *,
        loss: Any = None,
        grad_norm: Any = None,
        grads: Any = None,
        loss_scale: Any = None,
        scaler_handled: bool = False,
        force: bool = False,
    ) -> Optional[dict]:
        """Probe at the configured cadence (``force=True`` probes
        regardless). Values may be device arrays — they are only coerced
        (synced) on probe steps. ``scaler_handled=True`` means a dynamic
        loss scaler owns grad overflow on this step (it skips the update
        and backs off): non-finite *gradients* then count as
        ``scaler_skips`` instead of latching — that is the scaler doing
        its job, and the backoff staircase is already in the trajectory.
        A non-finite **loss** always latches. Returns the probe record,
        or None when this step is off-cadence."""
        if not force and (self.every <= 0 or step % self.every != 0):
            return None
        self.probes += 1
        bad_leaf = None
        bad_value = None
        import math

        scale = _scalar(loss_scale)
        if scale is not None and scale != self._last_scale:
            if self._last_scale is not None and scale < self._last_scale:
                self.scale_backoffs += 1
            self._last_scale = scale
            self.scale_trajectory.append((int(step), scale))
            del self.scale_trajectory[: -self._max_trajectory]
            self.log.event("loss_scale", step=int(step), scale=scale, backoffs=self.scale_backoffs)

        for name, value in (("loss", loss), ("grad_norm", grad_norm)):
            v = _scalar(value)
            if v is not None and not math.isfinite(v):
                bad_leaf, bad_value = name, v
                break
        if bad_leaf is None and grads is not None:
            path = _tree_first_nonfinite(grads)
            if path is not None:
                bad_leaf = f"grads{path}"

        record = {"step": int(step), "bad_leaf": bad_leaf}
        if bad_leaf is not None and bad_leaf != "loss" and scaler_handled:
            self.scaler_skips += 1
            self.log.event("nonfinite_skipped", step=int(step), leaf=bad_leaf, loss_scale=scale)
            record["scaler_handled"] = True
            return record
        if bad_leaf is not None and self.nonfinite_event is None:
            self.nonfinite_event = self.log.event(
                "nonfinite",
                severity="warning",
                step=int(step),
                leaf=bad_leaf,
                value=str(bad_value) if bad_value is not None else "nan/inf",
                loss_scale=scale,
                recent_loss_scales=[s for _, s in self.scale_trajectory[-8:]],
            )
        return record

    def summary(self) -> dict:
        out: dict = {
            "probes": self.probes,
            "nonfinite": self.nonfinite_event is not None,
            "scaler_skips": self.scaler_skips,
        }
        if self.nonfinite_event is not None:
            out["first_bad_leaf"] = self.nonfinite_event.get("leaf")
            out["nonfinite_step"] = self.nonfinite_event.get("step")
        if self.scale_trajectory:
            scales = [s for _, s in self.scale_trajectory]
            out["loss_scale"] = {
                "current": scales[-1],
                "min": min(scales),
                "max": max(scales),
                "backoffs": self.scale_backoffs,
            }
        return out
