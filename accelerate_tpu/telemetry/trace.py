"""Fleet-wide request tracing: one trace per ``submit()``, spans per
serving phase, exports an operator can load.

The serving stack already *aggregates* well (``ServingMetrics`` windows,
``telemetry summarize``), but aggregates cannot answer the first
production question: *where did this request's p95 TTFT go* once it
crossed router -> prefill replica -> KV handoff -> decode replica ->
(maybe) failover. This module holds the per-request answer:

* a :class:`Tracer` mints one trace id per ``FleetRouter.submit()`` /
  ``ServingEngine.submit()`` and collects :class:`Span` segments —
  ``queue_wait``, ``admit``, each prefill chunk window, ``kv_handoff``,
  ``decode`` (per-tick, aggregated into windows), ``preempt`` /
  ``resume``, ``failover``, and ``drain`` migration;
* segments are **frontier-contiguous**: each new segment covers the gap
  since the trace's last covered timestamp, so the segment sum
  reconciles with the request's end-to-end latency by construction (the
  property ``bench_serving.py --trace`` gates on). Compute-only timings
  ride in span meta (``compute_ms``) where a predictor cross-check needs
  them (:mod:`~accelerate_tpu.telemetry.critpath`);
* the trace id rides the request record through
  ``FleetRouter``/``ServingEngine``/``scheduling.py``, is serialized
  inside the ``HandoffCodec`` blob (schema v2; v1 blobs still decode),
  and rides ``export_inflight`` snapshots — traces survive disaggregated
  dispatch and failover, and the ROADMAP-item-1 socket transport
  inherits a context field instead of retrofitting one;
* exports: JSONL (eventlog-compatible ``trace.*`` span records + one
  ``trace_complete`` event, merged by ``telemetry summarize``) and
  Chrome trace-event JSON loadable in Perfetto (one ``tid`` per
  request).

jax is never imported here — ``accelerate-tpu trace ...`` runs on a
box with nothing but the stdlib.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

#: segment classes a trace may carry, in rough request-lifecycle order.
SEGMENTS = (
    "queue_wait",
    "admit",
    "prefill",
    "kv_handoff",
    "decode",
    "preempt",
    "resume",
    "failover",
    "drain",
)

#: eventlog record-name prefix for exported span segments.
TRACE_EVENT_PREFIX = "trace."

#: terminal trace statuses (``open`` is the only non-terminal one).
STATUSES = ("open", "ok", "shed", "cancelled", "lost", "failed")


@dataclass
class Span:
    """One contiguous segment of a request's wall-clock timeline."""

    name: str
    t0: float
    t1: float
    meta: dict = field(default_factory=dict)

    @property
    def dur_ms(self) -> float:
        return max(0.0, (self.t1 - self.t0) * 1000.0)


@dataclass
class Trace:
    """One request's timeline: id, status, and its segment spans."""

    id: int
    t0: float
    meta: dict = field(default_factory=dict)
    spans: list = field(default_factory=list)
    t1: Optional[float] = None
    status: str = "open"
    #: end of the last covered segment — the next span starts here.
    frontier: float = 0.0
    #: name of the mergeable open window (decode tick aggregation).
    window: Optional[str] = None

    def to_dict(self) -> dict:
        dur = ((self.t1 if self.t1 is not None else self.frontier) - self.t0) * 1000.0
        return {
            "id": self.id,
            "t0": self.t0,
            "status": self.status,
            "dur_ms": round(max(0.0, dur), 3),
            "meta": dict(self.meta),
            "spans": [
                {
                    "name": s.name,
                    "t0_ms": round((s.t0 - self.t0) * 1000.0, 3),
                    "dur_ms": round(s.dur_ms, 3),
                    **s.meta,
                }
                for s in self.spans
            ],
        }


@dataclass
class TraceConfig:
    """Knobs for ``FleetRouter(trace=...)`` / ``TelemetryKwargs``."""

    enabled: bool = True
    #: completed traces retained in memory (served by ``/traces``).
    max_traces: int = 4096
    #: per-replica flight recorder (see :mod:`~.flightrec`).
    flight_recorder: bool = True
    flight_capacity: int = 256
    #: directory for crash dumps; ``None`` keeps dumps in memory only.
    flight_dump_dir: Optional[str] = None
    #: cross-check each segment against its predictor (see :mod:`~.critpath`).
    drift_check: bool = True
    drift_thresholds: Optional[dict] = None

    def __post_init__(self):
        if self.max_traces < 1:
            raise ValueError(f"max_traces must be >= 1, got {self.max_traces}")
        if self.flight_capacity < 8:
            raise ValueError(f"flight_capacity must be >= 8, got {self.flight_capacity}")


class Tracer:
    """Thread-safe collector for request traces.

    Instrumentation sites call :meth:`seg` (one distinct span per call —
    prefill chunk windows, handoff, failover) or :meth:`window`
    (consecutive same-name calls merge — per-tick decode aggregation).
    Both are frontier-contiguous; mutation is O(1) under one ``RLock``
    and nothing blocking ever runs under it (export/formatting snapshot
    first, format outside — the TPU903 discipline).
    """

    def __init__(
        self,
        *,
        max_traces: int = 4096,
        clock: Callable[[], float] = time.monotonic,
        log=None,
        on_finish: Optional[Callable[[dict], None]] = None,
    ):
        self._lock = threading.RLock()
        self._clock = clock
        self._ids = itertools.count(1)
        self._open: dict[int, Trace] = {}
        self._done: list[dict] = []
        self._max_traces = max(1, int(max_traces))
        self.log = log
        self.on_finish = on_finish
        self.started = 0
        self.finished = 0

    # ------------------------------------------------------------------ #
    # recording surface (called from serving hot paths; cheap, guarded)
    # ------------------------------------------------------------------ #

    def start(self, **meta) -> int:
        """Mint a trace; the returned id is the context that rides the
        request record (and the handoff blob / failover snapshot)."""
        now = self._clock()
        with self._lock:
            tid = next(self._ids)
            self._open[tid] = Trace(id=tid, t0=now, meta=dict(meta), frontier=now)
            self.started += 1
        return tid

    def attach(self, trace_id: Optional[int], **meta) -> None:
        """Merge ``meta`` into an open trace (fuid, uid, ttft...)."""
        if trace_id is None:
            return
        with self._lock:
            tr = self._open.get(trace_id)
            if tr is not None:
                tr.meta.update(meta)

    def seg(self, trace_id: Optional[int], name: str, *, end: Optional[float] = None, **meta) -> None:
        """Close the segment ``[frontier, end]`` as one distinct span."""
        if trace_id is None:
            return
        end = self._clock() if end is None else end
        with self._lock:
            tr = self._open.get(trace_id)
            if tr is None:
                return
            tr.spans.append(Span(name, tr.frontier, max(tr.frontier, end), meta))
            tr.frontier = max(tr.frontier, end)
            tr.window = None

    def window(
        self, trace_id: Optional[int], name: str, *, end: Optional[float] = None, tokens: int = 0, **meta
    ) -> None:
        """Like :meth:`seg`, but consecutive same-name windows merge into
        one span (``tokens`` accumulates) — per-tick decode aggregation."""
        if trace_id is None:
            return
        end = self._clock() if end is None else end
        with self._lock:
            tr = self._open.get(trace_id)
            if tr is None:
                return
            end = max(tr.frontier, end)
            if tr.window == name and tr.spans and tr.spans[-1].name == name:
                span = tr.spans[-1]
                span.t1 = end
                span.meta["tokens"] = span.meta.get("tokens", 0) + int(tokens)
                span.meta.update(meta)
            else:
                m = dict(meta)
                m["tokens"] = int(tokens)
                tr.spans.append(Span(name, tr.frontier, end, m))
                tr.window = name
            tr.frontier = end

    def finish(self, trace_id: Optional[int], status: str = "ok", **meta) -> Optional[dict]:
        """Seal the trace, move it to the completed ring, export its span
        records to the attached eventlog, and run the ``on_finish`` hook
        (the critical-path drift monitor). Returns the trace dict."""
        if trace_id is None:
            return None
        now = self._clock()
        with self._lock:
            tr = self._open.pop(trace_id, None)
            if tr is None:
                return None
            tr.t1 = max(tr.frontier, now)
            tr.status = status
            tr.meta.update(meta)
            self.finished += 1
            out = tr.to_dict()
            self._done.append(out)
            if len(self._done) > self._max_traces:
                del self._done[: len(self._done) - self._max_traces]
        # formatting + hooks OUTSIDE the lock (log may flush to disk)
        log = self.log
        if log is not None:
            _emit_trace(log, out)
        hook = self.on_finish
        if hook is not None:
            hook(out)
        return out

    def discard(self, trace_id: Optional[int]) -> None:
        """Drop an open trace without exporting (duplicate-submit paths)."""
        if trace_id is None:
            return
        with self._lock:
            self._open.pop(trace_id, None)

    # ------------------------------------------------------------------ #
    # read surface
    # ------------------------------------------------------------------ #

    def completed(self, n: Optional[int] = None) -> list[dict]:
        """Most recent ``n`` completed traces (all when ``n`` is None)."""
        with self._lock:
            out = list(self._done)
        return out if n is None else out[-int(n):]

    def open_spans(self) -> list[dict]:
        """Snapshot of in-flight traces — the flight recorder dumps this
        next to the last-N event tail on a crash."""
        now = self._clock()
        with self._lock:
            snap = [
                {
                    "trace": tr.id,
                    "age_ms": round((now - tr.t0) * 1000.0, 3),
                    "segment": tr.spans[-1].name if tr.spans else None,
                    "spans": len(tr.spans),
                    "meta": dict(tr.meta),
                }
                for tr in self._open.values()
            ]
        return snap

    # ------------------------------------------------------------------ #
    # exports
    # ------------------------------------------------------------------ #

    def export_jsonl(self, path: str) -> int:
        """Write completed traces as eventlog-compatible JSONL (the same
        records the live log receives); returns the trace count."""
        from .eventlog import EventLog

        traces = self.completed()
        log = EventLog(path, rank=0, main_process_only=False, buffer_lines=1024)
        try:
            for tr in traces:
                _emit_trace(log, tr)
        finally:
            log.close()
        return len(traces)

    def export_chrome(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable); writes ``path``
        when given and returns the document."""
        doc = chrome_trace(self.completed())
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


def _emit_trace(log, trace: dict) -> None:
    """Emit one completed trace into an :class:`EventLog`: a ``trace.*``
    span record per segment, then one ``trace_complete`` event carrying
    the per-class totals."""
    totals: dict[str, float] = {}
    for sp in trace["spans"]:
        fields = {k: v for k, v in sp.items() if k != "name"}
        log.emit("span", TRACE_EVENT_PREFIX + sp["name"], trace=trace["id"], **fields)
        totals[sp["name"]] = round(totals.get(sp["name"], 0.0) + sp["dur_ms"], 3)
    log.event(
        "trace_complete",
        trace=trace["id"],
        status=trace["status"],
        dur_ms=trace["dur_ms"],
        segments=totals,
        **{k: v for k, v in trace["meta"].items() if isinstance(v, (int, float, str, bool))},
    )


def traces_from_events(events: list[dict]) -> list[dict]:
    """Reconstruct trace dicts from eventlog records (the inverse of
    :func:`_emit_trace`) — how the jax-free ``accelerate-tpu trace``
    CLI and the ``telemetry summarize`` traces section read a JSONL."""
    by_id: dict[int, dict] = {}
    for rec in events:
        name = rec.get("name", "")
        tid = rec.get("trace")
        if tid is None:
            continue
        if rec.get("kind") == "span" and name.startswith(TRACE_EVENT_PREFIX):
            tr = by_id.setdefault(tid, {"id": tid, "status": "open", "dur_ms": 0.0, "meta": {}, "spans": []})
            span = {k: v for k, v in rec.items() if k not in ("v", "seq", "ts", "rank", "kind", "name", "trace")}
            span["name"] = name[len(TRACE_EVENT_PREFIX):]
            tr["spans"].append(span)
        elif rec.get("kind") == "event" and name == "trace_complete":
            tr = by_id.setdefault(tid, {"id": tid, "status": "open", "dur_ms": 0.0, "meta": {}, "spans": []})
            tr["status"] = rec.get("status", "ok")
            tr["dur_ms"] = rec.get("dur_ms", tr["dur_ms"])
            # anchor an absolute start so chrome export can place the trace
            tr["t0"] = rec.get("ts", 0.0) - tr["dur_ms"] / 1000.0
            tr["meta"] = {
                k: v
                for k, v in rec.items()
                if k not in ("v", "seq", "ts", "rank", "kind", "name", "trace", "status", "dur_ms", "segments", "severity")
            }
    return list(by_id.values())


def chrome_trace(traces: list[dict]) -> dict:
    """Chrome trace-event document: ``ph:"X"`` complete events, one
    ``tid`` per request, span meta in ``args`` — drop the file on
    https://ui.perfetto.dev and read the decomposition off the timeline."""
    base = min((tr.get("t0", 0.0) for tr in traces), default=0.0)
    out: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0, "args": {"name": "accelerate_tpu serving"}}
    ]
    for tr in traces:
        label = tr.get("meta", {}).get("fuid", tr.get("meta", {}).get("uid", tr["id"]))
        out.append(
            {"ph": "M", "name": "thread_name", "pid": 0, "tid": tr["id"], "args": {"name": f"request {label}"}}
        )
        t0 = tr.get("t0", 0.0)
        for sp in tr["spans"]:
            args = {k: v for k, v in sp.items() if k not in ("name", "t0_ms", "dur_ms")}
            args["status"] = tr.get("status", "open")
            out.append(
                {
                    "name": sp["name"],
                    "cat": "request",
                    "ph": "X",
                    "ts": round((t0 - base) * 1e6 + sp.get("t0_ms", 0.0) * 1e3, 3),
                    "dur": round(sp.get("dur_ms", 0.0) * 1e3, 3),
                    "pid": 0,
                    "tid": tr["id"],
                    "args": args,
                }
            )
    return {"displayTimeUnit": "ms", "traceEvents": out}
