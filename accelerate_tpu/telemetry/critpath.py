"""Per-request critical-path decomposition, cross-checked against the
analyzers that predicted each segment.

:func:`decompose` turns completed traces
(:mod:`~accelerate_tpu.telemetry.trace`) into the operator table:
segment p50/p95 per class, per-request segment sums, and the share of
end-to-end latency each class claims. :class:`CritPathMonitor` is the
live half — the house predicted-vs-measured discipline applied per
request:

* ``queue_wait``  vs the scheduler's own accounting (``on_admit``'s
  ``queue_wait_ms``, carried in span meta as ``accounted_ms``);
* ``prefill``     vs ``perfmodel``/``costmodel.prefill_compute_us``
  (span meta ``compute_ms`` — the compute-only timing, not the
  frontier span which absorbs queueing);
* ``kv_handoff``  vs ``costmodel.price_kv_handoff`` (``moved_bytes``
  must equal ``predicted_bytes`` byte-for-byte);
* ``failover``    vs ``costmodel.price_failover`` (same byte equality
  on the KV path).

Each segment class gets ONE latched ``trace_drift`` warning — the
``hbm_drift`` / ``perf_model_drift`` discipline: the first excursion is
signal, the next thousand are noise. ``reset()`` re-arms (e.g. after a
fleet reconfiguration). Stdlib-only; predictors arrive as injected
callables so this module never imports jax.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

#: segment classes with a live predictor cross-check.
CHECKED_SEGMENTS = ("queue_wait", "prefill", "kv_handoff", "failover")

#: default relative-error latch thresholds per checked class. Byte
#: checks (handoff/failover) are exact — any mismatch latches; time
#: checks latch past the threshold AND an absolute floor (tiny segments
#: under coarse clocks are noise, the hbm_sampler lesson).
DEFAULT_THRESHOLDS = {
    "queue_wait": 0.5,
    "prefill": 2.0,
    "kv_handoff": 0.0,
    "failover": 0.0,
}

#: absolute floor (ms) below which a time-segment excursion never latches.
DEFAULT_MIN_MS = 2.0


def _percentile(values: list, q: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))
    return xs[idx]


def decompose(traces: list[dict]) -> dict:
    """Aggregate completed traces into the critical-path report.

    Returns ``{"count", "completed", "by_class": {seg: {count, total_ms,
    p50_ms, p95_ms, share}}, "requests": [...]}`` where ``share`` is the
    class's fraction of summed end-to-end latency across completed
    requests."""
    by_class: dict[str, list] = {}
    requests = []
    total_e2e = 0.0
    completed = 0
    for tr in traces:
        segs: dict[str, float] = {}
        for sp in tr.get("spans", []):
            segs[sp["name"]] = round(segs.get(sp["name"], 0.0) + sp.get("dur_ms", 0.0), 3)
            by_class.setdefault(sp["name"], []).append(sp.get("dur_ms", 0.0))
        seg_sum = round(sum(segs.values()), 3)
        row = {
            "id": tr.get("id"),
            "status": tr.get("status", "open"),
            "dur_ms": tr.get("dur_ms", 0.0),
            "segment_sum_ms": seg_sum,
            "segments": segs,
        }
        for key in ("fuid", "uid"):
            if key in tr.get("meta", {}):
                row[key] = tr["meta"][key]
        requests.append(row)
        if tr.get("status") == "ok":
            completed += 1
            total_e2e += tr.get("dur_ms", 0.0)
    table = {}
    for name, durs in sorted(by_class.items()):
        total = sum(durs)
        table[name] = {
            "count": len(durs),
            "total_ms": round(total, 3),
            "p50_ms": round(_percentile(durs, 0.50), 3),
            "p95_ms": round(_percentile(durs, 0.95), 3),
            "share": round(total / total_e2e, 4) if total_e2e > 0 else 0.0,
        }
    return {"count": len(traces), "completed": completed, "by_class": table, "requests": requests}


def render_critpath(report: dict, *, drift: Optional[list] = None) -> str:
    """Text table for the CLI / summarize ``traces:`` section body."""
    lines = [f"traces: {report['count']} recorded, {report['completed']} completed ok"]
    if report["by_class"]:
        lines.append("    segment         count   p50_ms    p95_ms    total_ms  share")
        for name, row in report["by_class"].items():
            lines.append(
                f"    {name:<15} {row['count']:>5} {row['p50_ms']:>9.3f} {row['p95_ms']:>9.3f}"
                f" {row['total_ms']:>11.3f}  {row['share']:.1%}"
            )
    for d in drift or []:
        lines.append(
            f"    DRIFT: {d['segment']} {d['check']}: observed {d['observed']} vs predicted "
            f"{d['predicted']} (rel {d['rel_error']:.2f}, trace {d['trace']})"
        )
    return "\n".join(lines)


class CritPathMonitor:
    """Live per-request drift checks with one latched warning per
    segment class, wired as ``Tracer(on_finish=monitor.observe)``."""

    def __init__(
        self,
        log=None,
        *,
        price_prefill_us: Optional[Callable[[int], float]] = None,
        thresholds: Optional[dict] = None,
        min_ms: float = DEFAULT_MIN_MS,
    ):
        self.log = log
        self.price_prefill_us = price_prefill_us
        self.thresholds = dict(DEFAULT_THRESHOLDS)
        if thresholds:
            self.thresholds.update(thresholds)
        self.min_ms = float(min_ms)
        #: segment class -> the latched trace_drift record (the latch).
        self.drift_events: dict[str, dict] = {}
        self.observed = 0

    def reset(self) -> None:
        """Re-arm every latch (the ``set_static_step_estimate`` move)."""
        self.drift_events = {}

    # ------------------------------------------------------------------ #

    def observe(self, trace: dict) -> None:
        """Cross-check one completed trace; latch at most one
        ``trace_drift`` per segment class, ever."""
        self.observed += 1
        if trace.get("status") not in ("ok", "lost"):
            return
        for check in self._checks(trace):
            seg = check["segment"]
            if seg in self.drift_events:
                continue
            rec = dict(check)
            rec["trace"] = trace.get("id")
            if self.log is not None:
                rec = self.log.event("trace_drift", severity="warning", **rec)
            self.drift_events[seg] = rec

    def _checks(self, trace: dict):
        """Yield drift dicts for every segment whose observation left its
        predictor's tolerance."""
        for sp in trace.get("spans", []):
            name = sp["name"]
            if name == "queue_wait" and sp.get("accounted_ms") is not None:
                yield from self._time_check(name, "scheduler_accounting", sp["dur_ms"], sp["accounted_ms"])
            elif name == "prefill" and self.price_prefill_us is not None and sp.get("compute_ms") is not None:
                tokens = int(sp.get("tokens", 0))
                if tokens > 0:
                    predicted_ms = float(self.price_prefill_us(tokens)) / 1000.0
                    yield from self._time_check(name, "prefill_compute_us", sp["compute_ms"], predicted_ms)
            elif name in ("kv_handoff", "failover", "drain"):
                moved = sp.get("moved_bytes")
                predicted = sp.get("predicted_bytes")
                if moved is None or predicted is None:
                    continue
                if sp.get("path", "handoff") != "handoff":
                    continue  # recompute failovers move no KV by design
                if int(moved) != int(predicted):
                    seg = "failover" if name == "drain" else name
                    rel = abs(moved - predicted) / max(1, predicted)
                    yield {
                        "segment": seg,
                        "check": "price_kv_handoff" if seg == "kv_handoff" else "price_failover",
                        "observed": int(moved),
                        "predicted": int(predicted),
                        "rel_error": round(rel, 4),
                        "threshold": self.thresholds.get(seg, 0.0),
                    }

    def _time_check(self, segment: str, check: str, observed_ms: float, predicted_ms: float):
        threshold = self.thresholds.get(segment, 1.0)
        rel = abs(observed_ms - predicted_ms) / max(predicted_ms, 1e-9)
        if rel > threshold and abs(observed_ms - predicted_ms) > self.min_ms:
            yield {
                "segment": segment,
                "check": check,
                "observed": round(observed_ms, 3),
                "predicted": round(predicted_ms, 3),
                "rel_error": round(rel, 4),
                "threshold": threshold,
            }
