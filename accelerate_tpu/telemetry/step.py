"""Step timeline: wall-time split, compile attribution, and the recompile
watchdog.

On TPU a ``step(batch)`` call is three different costs wearing one wall
clock: the host waiting for data, the python+dispatch that enqueues the
XLA program, and the device actually executing it. ``StepTelemetry``
fences with ``block_until_ready`` on the step's outputs so the three are
separable:

* ``data_wait_ms`` — time between the previous step's fence completing and
  this call starting (dataloader + host-side glue);
* ``dispatch_ms``  — time inside the wrapped call before it returns
  (tracing/compile on a cache miss, microseconds on a hit);
* ``execute_ms``   — time blocked on the outputs after dispatch (device
  compute the dispatch didn't already overlap).

The first call's dispatch is attributed as **compile time** (jit blocks in
the caller while XLA compiles), as is any later call the watchdog flags.

The **recompile watchdog** is the runtime twin of the static TPU2xx lint
rules: after ``warmup_steps`` calls, any input signature (pytree structure
+ shape/dtype per leaf) never seen before is a jit cache miss — silent
recompiles are the classic TPU throughput killer (a drifting batch
dimension recompiles every step). Each miss emits ONE ``recompile``
warning event naming exactly which avals changed versus the previous call.
When the wrapped callable exposes jit's ``_cache_size`` (``jax.jit``
functions and ``build_train_step``'s ``step._jitted`` do), cache growth is
cross-checked too, catching drift a shape signature can't see (e.g.
weak-type promotion).

The **perf-model drift check** is the roofline twin of the HBM drift
check: when a static step-time prediction is attached
(:meth:`StepTelemetry.set_static_step_estimate` — what
``Accelerator.perf_check`` seeds), the observed steady-state busy time
(dispatch + execute, the part the roofline models) is compared against it
once enough steady steps exist, and ONE ``perf_model_drift`` warning
event fires when they disagree by more than the threshold — either the
static model is mispricing an op (fix ``analysis.perfmodel``) or the
program is doing work the author didn't price (fix the program).

Per-step records are kept in a bounded in-memory deque (so ``summary()``
works with no event log at all) and mirrored to an :class:`EventLog` when
one is attached.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Callable, Optional

from .eventlog import EventLog


def _aval_str(leaf) -> str:
    """``f32[8,128]``-style signature for one pytree leaf."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    return type(leaf).__name__


def signature_of(tree) -> tuple:
    """Hashable (path, aval-string) signature of an input pytree — the
    host-side proxy for jit's cache key. Uses jax's path flattening when
    jax is already imported, else a plain structural walk (telemetry must
    not initialise the backend)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        from ..parallel.sharding import path_str

        return tuple((path_str(kp), _aval_str(leaf)) for kp, leaf in flat)
    out = []

    def walk(node, path):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{path}.{k}" if path else str(k))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
        else:
            out.append((path, _aval_str(node)))

    walk(tree, "")
    return tuple(out)


class _PathCachedSignature:
    """Per-instance fast signature: path strings are computed ONCE per
    pytree structure (treedef) and cached — the per-step cost is one
    ``tree_flatten`` plus an aval string per leaf (~2 us for a typical
    batch), which is what keeps the watchdog inside the <2% overhead
    budget on small steps."""

    def __init__(self):
        self._paths: dict = {}  # treedef -> tuple of path strings

    def __call__(self, tree) -> tuple:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return signature_of(tree)
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        paths = self._paths.get(treedef)
        if paths is None:
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            from ..parallel.sharding import path_str

            paths = tuple(path_str(kp) for kp, _ in flat)
            self._paths[treedef] = paths
        return tuple(zip(paths, (_aval_str(l) for l in leaves)))


_AVAL_RE = None  # compiled lazily (re import kept off the hot path)


def suggest_buckets(old: Optional[tuple], new: tuple) -> list[str]:
    """Pad-shape suggestions that would have avoided a watchdog miss: for
    each input whose SHAPE drifted between two signatures, the aval with
    every drifting dim padded to the next power of two covering both
    sides — the fix auto-bucketing applies automatically
    (:class:`accelerate_tpu.aot.ShapeBucketer`), named here so users
    running without it still get the actionable change. Dtype changes
    and rank changes yield no suggestion (padding can't fix those)."""
    global _AVAL_RE
    if not old or not new:
        return []
    if _AVAL_RE is None:
        import re

        _AVAL_RE = re.compile(r"^([A-Za-z0-9_]+)\[([0-9,]*)\]$")
    from ..aot.bucketing import next_pow2

    out = []
    old_map = dict(old)
    for path, aval in new:
        prev = old_map.get(path)
        if prev is None or prev == aval:
            continue
        m_new, m_old = _AVAL_RE.match(aval), _AVAL_RE.match(prev)
        if not m_new or not m_old or m_new.group(1) != m_old.group(1):
            continue  # dtype changed (or unparseable): not a padding problem
        nd = [int(d) for d in m_new.group(2).split(",") if d]
        od = [int(d) for d in m_old.group(2).split(",") if d]
        if len(nd) != len(od):
            continue  # rank changed
        padded = [n if n == o else next_pow2(max(n, o)) for n, o in zip(nd, od)]
        if padded == nd:
            continue  # already at the covering size
        out.append(f"{path}: pad to {m_new.group(1)}[{','.join(str(d) for d in padded)}]")
    return out


def diff_signatures(old: Optional[tuple], new: tuple) -> list[str]:
    """Human strings naming what changed between two input signatures."""
    if old is None:
        return [f"{path}: {aval} (new input)" for path, aval in new]
    old_map, new_map = dict(old), dict(new)
    changes = []
    for path, aval in new:
        prev = old_map.get(path)
        if prev is None:
            changes.append(f"{path}: (absent) -> {aval}")
        elif prev != aval:
            changes.append(f"{path}: {prev} -> {aval}")
    for path, aval in old:
        if path not in new_map:
            changes.append(f"{path}: {aval} -> (absent)")
    return changes or ["input signature unchanged (cache key drift invisible to shapes — "
                       "likely weak_type/sharding)"]


def _block_until_ready(out):
    """Fence on every array leaf of ``out`` (non-arrays pass through)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return
    for leaf in jax.tree_util.tree_leaves(out):
        fn = getattr(leaf, "block_until_ready", None)
        if fn is not None:
            fn()


class StepTelemetry:
    """Timeline + watchdog for a repeatedly-called step function.

    Two usage shapes::

        st = StepTelemetry(log)
        step = st.wrap(step)          # fast path: instruments every call
        ...
        with st.step() as s:          # imperative path (accumulate block)
            loss = accelerator.backward(loss_fn, batch)
            s.done(loss)              # optional: what to fence on

    ``flops_per_step`` + ``peak_flops_per_device`` (+ ``n_devices``) turn
    each steady-state record into an MFU sample. ``fence=False`` drops the
    ``block_until_ready`` (execute time then reads 0 — use when the loop
    already fences, e.g. a ``float(loss)`` per step).

    ``warmup_steps`` defaults to 2, not 1: the first call compiles, and
    the SECOND may legitimately compile a second program variant when
    sharding propagation re-lays-out carried state (``build_train_step``'s
    gradient buffer comes back from step 1 with propagated shardings, a
    different jit cache key). Anything past warmup is a real miss.
    """

    def __init__(
        self,
        log: Optional[EventLog] = None,
        *,
        warmup_steps: int = 2,
        fence: bool = True,
        watchdog: bool = True,
        flops_per_step: Optional[float] = None,
        peak_flops_per_device: Optional[float] = None,
        n_devices: int = 1,
        max_records: int = 4096,
        clock=time.perf_counter,
    ):
        self.log = log if log is not None else EventLog(None)
        self.warmup_steps = max(0, int(warmup_steps))
        self.fence = fence
        self.watchdog = watchdog
        self.flops_per_step = flops_per_step
        self.peak_flops_per_device = peak_flops_per_device
        self.n_devices = max(1, int(n_devices))
        self._clock = clock

        self.step_index = 0
        self.recompiles = 0
        self.compile_ms = 0.0  # summed over first step + every detected miss
        self.records: collections.deque = collections.deque(maxlen=max_records)
        self.recompile_events: list[dict] = []
        # perf-model drift check (seeded by set_static_step_estimate)
        self.static_step_ms: Optional[float] = None
        self.perf_drift_threshold = 0.5
        self.perf_drift_min_steady = 5
        self.perf_drift_event: Optional[dict] = None
        self._signature = _PathCachedSignature()
        self._last_fence_end: Optional[float] = None
        self._cm_watchdog: Optional[_WatchdogState] = None  # context-manager path's
        self.on_step: Optional[Callable[[dict], None]] = None  # post-record hook

    # ------------------------------------------------------------------ #
    # instrumentation
    # ------------------------------------------------------------------ #

    def wrap(self, step_fn: Callable, *, name: str = "step") -> Callable:
        """Instrumented twin of ``step_fn``; every call records one step.
        The telemetry object rides on the wrapper as ``.telemetry``.

        Watchdog state (warmup counter, seen signatures, jit cache probe)
        is PER WRAPPER: a second wrapped function — or one wrapped after
        imperative steps already ran — gets its own warmup, so its first
        compiles are attributed, not misreported as recompiles."""
        probe = step_fn if hasattr(step_fn, "_cache_size") else getattr(step_fn, "_jitted", None)
        if probe is None or not hasattr(probe, "_cache_size"):
            probe = None
        wd = _WatchdogState(self.warmup_steps, probe)

        def instrumented(*args, **kwargs):
            sig = self._signature((args, kwargs)) if self.watchdog else None
            t_enter = self._clock()
            out = step_fn(*args, **kwargs)
            t_done = self._clock()
            if self.fence:
                _block_until_ready(out)
            t_fence = self._clock()
            self._record(name, sig, t_enter, t_done, t_fence, wd)
            return out

        instrumented.telemetry = self
        instrumented.__wrapped__ = step_fn
        return instrumented

    @contextlib.contextmanager
    def step(self, batch=None, *, name: str = "step"):
        """Context-manager form for imperative loops. ``batch`` (optional)
        feeds the watchdog; call ``handle.done(outputs)`` to mark dispatch
        complete and name what to fence on — otherwise the whole body
        counts as dispatch and the fence is skipped."""
        sig = self._signature(batch) if (self.watchdog and batch is not None) else None
        if self._cm_watchdog is None:
            self._cm_watchdog = _WatchdogState(self.warmup_steps, None)
        handle = _StepHandle(self._clock)
        t_enter = self._clock()
        yield handle
        t_done = handle.done_at if handle.done_at is not None else self._clock()
        if self.fence and handle.outputs is not None:
            _block_until_ready(handle.outputs)
        t_fence = self._clock()
        self._record(name, sig, t_enter, t_done, t_fence, self._cm_watchdog)

    # ------------------------------------------------------------------ #
    # internals
    # ------------------------------------------------------------------ #

    def _check_watchdog(self, wd: "_WatchdogState", sig) -> tuple[bool, list[str], bool]:
        """(is_miss, changed-aval strings, compiled_hint) for this call
        through the wrapper owning ``wd``. During warmup every signature
        is learned silently (the first compile of each shape bucket is
        expected) but a fresh signature / cache growth still flags the
        step as a compile step, keeping the steady-state stats clean;
        afterwards a never-seen signature — or jit cache growth with an
        unchanged signature — is a miss."""
        cache_grew = False
        if wd.probe is not None:
            try:
                size = wd.probe._cache_size()
                cache_grew = size > wd.probe_size
                wd.probe_size = size
            except Exception:
                wd.probe = None
        if not self.watchdog:
            return False, [], cache_grew
        in_warmup = wd.calls < wd.warmup
        if sig is not None:
            fresh = sig not in wd.seen
            wd.seen.add(sig)
        else:
            fresh = False
        if in_warmup:
            return False, [], cache_grew or fresh
        if sig is not None and fresh:
            return True, diff_signatures(wd.last_sig, sig), True
        if cache_grew:
            # signature unchanged (or untracked) but jit still compiled
            changed = diff_signatures(wd.last_sig, sig) if sig else [
                "jit cache grew with no tracked input change"
            ]
            return True, changed, True
        return False, [], False

    def _record(self, name, sig, t_enter, t_done, t_fence, wd: "_WatchdogState"):
        data_wait_ms = 0.0
        if self._last_fence_end is not None:
            data_wait_ms = max(0.0, (t_enter - self._last_fence_end) * 1000.0)
        dispatch_ms = (t_done - t_enter) * 1000.0
        execute_ms = (t_fence - t_done) * 1000.0
        self._last_fence_end = t_fence

        is_first = wd.calls == 0  # first call THROUGH THIS WRAPPER compiles
        miss, changed, compiled_hint = self._check_watchdog(wd, sig)
        if miss:
            self.recompiles += 1
            ev = self.log.event(
                "recompile",
                severity="warning",
                step=self.step_index,
                changed=changed,
                # the pad shape that would have avoided this miss (empty
                # when padding can't fix it — dtype/rank/structure drift)
                suggested_bucket=suggest_buckets(wd.last_sig, sig) if sig else [],
                count=self.recompiles,
            )
            self.recompile_events.append(ev)
        is_compile = is_first or miss or compiled_hint
        if is_compile:
            # on a miss/first call the dispatch segment IS the compile
            self.compile_ms += dispatch_ms

        rec = {
            "step": self.step_index,
            "dur_ms": round(data_wait_ms + dispatch_ms + execute_ms, 3),
            "data_wait_ms": round(data_wait_ms, 3),
            "dispatch_ms": round(dispatch_ms, 3),
            "execute_ms": round(execute_ms, 3),
            "compile": is_compile,
        }
        if (
            not is_compile
            and self.flops_per_step
            and self.peak_flops_per_device
            and (dispatch_ms + execute_ms) > 0
        ):
            step_s = (dispatch_ms + execute_ms) / 1000.0
            rec["mfu"] = round(
                self.flops_per_step / step_s / (self.peak_flops_per_device * self.n_devices), 5
            )
        self.log.emit("span", name, **rec)
        self.records.append(rec)
        if sig is not None:
            wd.last_sig = sig
        wd.calls += 1
        self.step_index += 1
        self._check_perf_drift()
        if self.on_step is not None:
            self.on_step(rec)

    # ------------------------------------------------------------------ #
    # perf-model drift (static roofline vs observed step split)
    # ------------------------------------------------------------------ #

    def set_static_step_estimate(self, predicted_ms: float, *, threshold: Optional[float] = None):
        """Attach a static step-time prediction (``Accelerator.perf_check``
        seeds ``PerfReport.predicted_step_ms`` here). Once
        ``perf_drift_min_steady`` steady records exist, the observed
        median busy time (dispatch + execute — the part the roofline
        models; data-wait is the loader's problem) is compared against it
        and ONE ``perf_model_drift`` warning fires past ``threshold``."""
        self.static_step_ms = float(predicted_ms)
        if threshold is not None:
            self.perf_drift_threshold = float(threshold)
        self.perf_drift_event = None  # a new estimate re-arms the check
        self.log.event("perf_static_estimate", predicted_ms=round(self.static_step_ms, 4))

    def observed_busy_ms(self) -> Optional[float]:
        """Median steady-state dispatch+execute ms (None before any
        steady record)."""
        steady = self.steady_records()
        if not steady:
            return None
        busy = sorted(r["dispatch_ms"] + r["execute_ms"] for r in steady)
        return round(busy[len(busy) // 2], 3)

    def _check_perf_drift(self):
        if self.perf_drift_event is not None or not self.static_step_ms:
            return
        steady = self.steady_records()
        if len(steady) < self.perf_drift_min_steady:
            return
        observed = self.observed_busy_ms()
        if not observed:
            return
        rel = abs(observed - self.static_step_ms) / self.static_step_ms
        if rel > self.perf_drift_threshold:
            self.perf_drift_event = self.log.event(
                "perf_model_drift",
                severity="warning",
                predicted_ms=round(self.static_step_ms, 4),
                observed_busy_ms=observed,
                rel_error=round(rel, 4),
                threshold=self.perf_drift_threshold,
            )

    # ------------------------------------------------------------------ #
    # summaries
    # ------------------------------------------------------------------ #

    def steady_records(self) -> list[dict]:
        return [r for r in self.records if not r["compile"]]

    def summary(self) -> dict:
        """p50/p95 step split, compile attribution, recompiles, MFU and
        goodput over the retained (steady-state) records."""
        steady = self.steady_records()
        durs = sorted(r["dur_ms"] for r in steady)
        out = {
            "steps": self.step_index,
            "steady_steps": len(steady),
            "compile_ms": round(self.compile_ms, 3),
            "recompiles": self.recompiles,
            "p50_step_ms": _pct(durs, 50),
            "p95_step_ms": _pct(durs, 95),
        }
        if steady:
            total = sum(r["dur_ms"] for r in steady)
            out["mean_data_wait_ms"] = round(sum(r["data_wait_ms"] for r in steady) / len(steady), 3)
            out["mean_dispatch_ms"] = round(sum(r["dispatch_ms"] for r in steady) / len(steady), 3)
            out["mean_execute_ms"] = round(sum(r["execute_ms"] for r in steady) / len(steady), 3)
            # goodput: fraction of steady wall time the device spent executing
            # (dispatch included when unfenced loops fold execute into it)
            busy = sum(r["dispatch_ms"] + r["execute_ms"] for r in steady)
            out["goodput"] = round(min(1.0, busy / total), 4) if total > 0 else None
            mfus = [r["mfu"] for r in steady if "mfu" in r]
            if mfus:
                out["mfu"] = round(sum(mfus) / len(mfus), 5)
        if self.static_step_ms:
            out["static_step_ms"] = round(self.static_step_ms, 4)
            observed = self.observed_busy_ms()
            if observed is not None:
                out["observed_busy_ms"] = observed
            out["perf_model_drift"] = self.perf_drift_event is not None
        return out


class _WatchdogState:
    """Per-wrapper watchdog bookkeeping: warmup counter, seen input
    signatures, last signature (for diff naming), and the jit cache-size
    probe. One per :meth:`StepTelemetry.wrap` call (and one shared by the
    context-manager path) — warmup is about a PROGRAM's compile history,
    not the run's global step count."""

    __slots__ = ("warmup", "calls", "seen", "last_sig", "probe", "probe_size")

    def __init__(self, warmup: int, probe=None):
        self.warmup = warmup
        self.calls = 0
        self.seen: set = set()
        self.last_sig: Optional[tuple] = None
        self.probe = probe
        self.probe_size = 0


class _StepHandle:
    """Yielded by :meth:`StepTelemetry.step`; ``done(outputs)`` marks the
    dispatch boundary and registers what the exit fence blocks on."""

    def __init__(self, clock):
        self._clock = clock
        self.outputs = None
        self.done_at: Optional[float] = None

    def done(self, outputs=None):
        self.done_at = self._clock()
        self.outputs = outputs
        return outputs


def _pct(sorted_vals: list, q: float) -> Optional[float]:
    """Nearest-rank percentile (no numpy needed at summarize time)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return round(sorted_vals[k], 3)
