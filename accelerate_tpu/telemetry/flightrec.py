"""Per-replica crash flight recorder: a bounded, preallocated ring of
the last N telemetry records, dumped whole when the replica dies.

Aggregate telemetry answers "how is the fleet doing"; the flight
recorder answers "what were the last 256 things *this* replica did
before it crashed". It taps the replica engine's
:class:`~accelerate_tpu.telemetry.eventlog.EventLog` (``add_tap``), so
every record the engine would log — admits, sheds, handoffs, replica
state flips, the poison/crash event itself — lands in the ring whether
or not a JSONL file is attached. On crash / quarantine / poison /
capacity-breaker trip the router calls :meth:`dump`, which snapshots:

* the event tail (ring order, oldest first — the injected fault's event
  is the last thing in it, which the ``ReplicaChaos`` tests assert);
* the in-flight request table the caller passes in;
* the tracer's open spans (requests caught mid-segment).

Host-concurrency discipline (this module is on the strict
``fleet-check`` path, TPU901-903): the ring is preallocated, the lock
is an ``RLock`` held only for O(1) slot assignment or a list copy, and
all formatting/JSON/file IO happens outside it. Recording never raises
— a flight recorder that can take down the engine it observes is worse
than none.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class FlightRecorder:
    """Bounded ring buffer of telemetry records + crash-dump writer."""

    def __init__(self, capacity: int = 256, *, name: str = "", clock=time.time):
        if capacity < 8:
            raise ValueError(f"capacity must be >= 8, got {capacity}")
        self.capacity = int(capacity)
        self.name = name
        self._clock = clock
        # preallocated: recording is slot assignment, never an append
        self._ring: list = [None] * self.capacity
        self._idx = 0
        self._total = 0
        self._lock = threading.RLock()
        self.dump_count = 0
        self.last_dump: Optional[dict] = None

    # ------------------------------------------------------------------ #
    # record path (EventLog tap; hot, must never raise or block)
    # ------------------------------------------------------------------ #

    def record(self, rec: dict) -> None:
        """Store one record dict in the ring. Tap target for
        ``EventLog.add_tap`` — called inline on the emitting thread."""
        with self._lock:
            self._ring[self._idx] = rec
            self._idx = (self._idx + 1) % self.capacity
            self._total += 1

    # ------------------------------------------------------------------ #
    # read path
    # ------------------------------------------------------------------ #

    def tail(self, n: Optional[int] = None) -> list:
        """The last ``n`` records, oldest first (all retained when None).
        Snapshot under the lock; no formatting happens in here."""
        with self._lock:
            if self._total < self.capacity:
                out = [r for r in self._ring[: self._idx]]
            else:
                out = self._ring[self._idx:] + self._ring[: self._idx]
        out = [r for r in out if r is not None]
        return out if n is None else out[-int(n):]

    def __len__(self) -> int:
        with self._lock:
            return min(self._total, self.capacity)

    # ------------------------------------------------------------------ #
    # dump path (cold; called when a replica leaves the fleet)
    # ------------------------------------------------------------------ #

    def dump(
        self,
        *,
        reason: str = "",
        inflight: Optional[list] = None,
        open_spans: Optional[list] = None,
        path: Optional[str] = None,
    ) -> dict:
        """Assemble a dump document and (optionally) write it to ``path``.

        The event tail is snapshotted under the lock; serialization and
        the file write happen outside it. Never raises — a failed write
        records itself in the returned document instead."""
        events = self.tail()
        doc = {
            "flight_recorder": self.name,
            "reason": reason,
            "ts": self._clock(),
            "capacity": self.capacity,
            "recorded_total": self._total,
            "events": events,
            "inflight": list(inflight) if inflight else [],
            "open_spans": list(open_spans) if open_spans else [],
        }
        if path is not None:
            try:
                parent = os.path.dirname(os.path.abspath(path))
                os.makedirs(parent, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(doc, f, default=_coerce)
                doc["path"] = path
            except OSError as e:
                doc["write_error"] = f"{type(e).__name__}: {e}"
        with self._lock:
            self.dump_count += 1
            self.last_dump = doc
        return doc


def _coerce(obj):
    """json fallback for numpy scalars and other strays in event fields."""
    fn = getattr(obj, "item", None)
    if callable(fn):
        try:
            return fn()
        except Exception:
            pass
    return repr(obj)


def read_dump(path: str) -> dict:
    """Load a dump file (the ``accelerate-tpu trace flight-dump`` input)."""
    with open(path) as f:
        return json.load(f)


def render_dump(doc: dict, *, tail: int = 16) -> str:
    """Human-readable dump transcript: header, in-flight table, open
    spans, then the last ``tail`` events oldest-first."""
    lines = [
        f"flight recorder {doc.get('flight_recorder') or '<unnamed>'}: "
        f"reason={doc.get('reason') or '<none>'} "
        f"recorded={doc.get('recorded_total', 0)} (ring {doc.get('capacity', '?')})",
    ]
    inflight = doc.get("inflight") or []
    lines.append(f"  in-flight requests: {len(inflight)}")
    for row in inflight:
        frag = " ".join(f"{k}={row[k]}" for k in sorted(row) if row[k] is not None)
        lines.append(f"    {frag}")
    spans = doc.get("open_spans") or []
    lines.append(f"  open spans: {len(spans)}")
    for row in spans:
        lines.append(
            f"    trace {row.get('trace')}: in {row.get('segment') or '<no segment>'} "
            f"for {row.get('age_ms', 0.0):.1f} ms ({row.get('spans', 0)} spans)"
        )
    events = (doc.get("events") or [])[-tail:]
    lines.append(f"  event tail (last {len(events)}):")
    for rec in events:
        extra = {
            k: v for k, v in rec.items() if k not in ("v", "seq", "ts", "rank", "kind", "name", "severity")
        }
        frag = " ".join(f"{k}={v}" for k, v in extra.items())
        sev = rec.get("severity")
        sev_frag = f" [{sev}]" if sev and sev != "info" else ""
        lines.append(f"    seq={rec.get('seq', '?')} {rec.get('kind')}:{rec.get('name')}{sev_frag} {frag}".rstrip())
    return "\n".join(lines)
