"""Scheduling policy for the continuous-batching serving engine.

:mod:`accelerate_tpu.serving` owns the *mechanism* (slots, caches, the
compiled prefill/decode programs); this module owns the *policy* — the
decisions a production scheduler makes every tick:

* **token budget**: each engine tick may spend at most ``token_budget``
  tokens of model compute. Active decodes claim theirs first
  (``n_decoding x tick_block``); the remainder is filled with *chunks*
  of pending prefills, so a long prompt streams into its cache across
  ticks instead of stalling every running decode for its whole prefill
  (the vLLM/Sarathi "chunked prefill" discipline). ``token_budget=None``
  disables interleaving — every admitted prefill runs to completion in
  its admission tick (the pre-scheduler behavior, and what
  ``mode="fifo"`` pins for A/B benchmarking);
* **priority-class admission**: ``submit(..., priority=...)`` — lower
  value admits sooner; ties admit FIFO by submission order. Preempted
  requests requeue with their original order key, so a resumed request
  never loses its place to later arrivals of the same class;
* **SLO-aware load shedding**: when queue depth (at submit) or queue
  wait (at admission) crosses the configured threshold, sheddable
  requests (``priority >= shed_priority_floor``) are rejected with a
  structured :class:`ShedError` and a ``shed`` telemetry event instead
  of silently queueing into a blown SLO. ``shed_action="deprioritize"``
  demotes instead of rejecting;
* **decode preemption**: under pool-block pressure (paged) or a
  priority inversion (dense, all slots busy and a strictly more
  important request waiting), the youngest lowest-priority decode
  releases its slot and KV blocks and requeues with its
  generated-so-far tokens; it resumes by prefix-style recomputation —
  token- and logprob-exact, because the recomputed K/V equals what the
  evicted cache held and the sampling key chain is carried across the
  preemption;
* **speculative gating**: with a draft model attached,
  ``speculative_priorities`` restricts the speculative tick to ticks
  where every decoding slot's priority opted in (greedy speculative
  decoding is target-exact regardless of draft-cache staleness, so
  mixing plain and speculative ticks costs only acceptance rate, never
  tokens).

Everything here is host-side policy over plain Python state — no jax.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence


class ShedError(RuntimeError):
    """Structured admission rejection (SLO load shedding).

    Raised by ``submit()`` when the queue-depth SLO is already blown, and
    by ``poll()``/``partial()``/``logprobs()`` for a request that was shed
    from the queue after exceeding the queue-wait SLO. Carries the
    decision context so a gateway can return a well-formed 429/503
    instead of parsing a message string.
    """

    def __init__(self, reason: str, uid: Optional[int] = None, priority: int = 0,
                 queue_depth: int = 0, queue_wait_ms: Optional[float] = None,
                 trace_id: Optional[int] = None):
        self.reason = reason
        self.uid = uid
        self.priority = priority
        self.queue_depth = queue_depth
        self.queue_wait_ms = queue_wait_ms
        # the request's distributed-tracing id (telemetry.trace), when the
        # engine/router was tracing — lets a gateway log a correlatable id
        self.trace_id = trace_id
        detail = f"request shed ({reason}): priority={priority} queue_depth={queue_depth}"
        if queue_wait_ms is not None:
            detail += f" queue_wait_ms={queue_wait_ms:.1f}"
        if uid is not None:
            detail = f"request {uid} shed ({reason}): priority={priority} queue_depth={queue_depth}"
        if trace_id is not None:
            detail += f" trace={trace_id}"
        super().__init__(detail)


@dataclasses.dataclass
class SchedulerConfig:
    """Knobs for the :class:`Scheduler`. The default configuration is
    behavior-preserving: unlimited budget, single priority class, no
    shedding, no preemption — ``ServingEngine`` without a config decodes
    exactly as before.

    ``mode``: ``"continuous"`` (token-budget interleaving, priorities,
    SLOs) or ``"fifo"`` (strict submission order, full prefill at
    admission, every other knob ignored — the A/B baseline the serving
    benchmark measures against).

    ``token_budget``: model-compute tokens one tick may spend; decodes
    claim ``n_decoding x tick_block`` first, prefill chunks fill the
    remainder. Size it above ``num_slots x tick_block`` plus at least
    one prefill chunk or prefill only progresses on underfull ticks
    (the engine always forces one unit of progress per tick, so no
    configuration can livelock). ``None`` = unlimited.

    ``max_queue_depth`` / ``max_queue_wait_s``: SLO thresholds —
    depth is checked at submit, wait at every admission pass. Only
    requests with ``priority >= shed_priority_floor`` are ever shed, so
    the default floor of 1 makes priority-0 traffic unsheddable.
    ``shed_action="deprioritize"`` demotes an over-SLO request to
    ``deprioritize_to`` (once) instead of rejecting it.

    ``enable_preemption``: allow a decoding slot with
    ``priority >= preempt_priority_floor`` to be evicted (requeued,
    resumed later by recompute) when a strictly more important request
    cannot be admitted — pool exhaustion in paged mode, no free slot in
    dense mode.

    ``speculative_priorities``: with a draft model, run the speculative
    tick only when every decoding slot's priority is in this set
    (``None`` = all priorities speculate — the engine's historical
    behavior).
    """

    mode: str = "continuous"
    token_budget: Optional[int] = None
    max_queue_depth: Optional[int] = None
    max_queue_wait_s: Optional[float] = None
    shed_priority_floor: int = 1
    shed_action: str = "reject"
    deprioritize_to: int = 99
    enable_preemption: bool = False
    preempt_priority_floor: int = 1
    speculative_priorities: Optional[tuple] = None

    def __post_init__(self):
        if self.mode not in ("continuous", "fifo"):
            raise ValueError(f"mode must be continuous|fifo, got {self.mode!r}")
        if self.token_budget is not None and self.token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got {self.token_budget}")
        if self.max_queue_depth is not None and self.max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if self.max_queue_wait_s is not None and self.max_queue_wait_s < 0:
            raise ValueError(f"max_queue_wait_s must be >= 0, got {self.max_queue_wait_s}")
        if self.shed_action not in ("reject", "deprioritize"):
            raise ValueError(f"shed_action must be reject|deprioritize, got {self.shed_action!r}")
        if self.speculative_priorities is not None:
            self.speculative_priorities = tuple(int(p) for p in self.speculative_priorities)


@dataclasses.dataclass
class RoutingConfig:
    """Fleet-level routing policy knobs
    (:class:`~accelerate_tpu.serving_fleet.FleetRouter`).

    ``policy``: how a request without prefix affinity picks a replica —
    ``"least_loaded"`` (min queued + active, ties to the lowest index)
    or ``"round_robin"``. Prefix affinity (a replica already holds the
    request's shared preamble in its radix cache) always wins over the
    policy: re-prefilling a cached preamble on a colder replica costs
    more than any load imbalance the policy could fix.

    ``max_fleet_queue_depth``: fleet-wide SLO admission gate — the sum
    of every replica's queue depth, checked at ``FleetRouter.submit``
    with the SAME priority-class semantics as the per-engine scheduler
    (only ``priority >= shed_priority_floor`` is sheddable, rejection is
    a structured :class:`ShedError`). Per-replica depth/wait SLOs keep
    riding each engine's own :class:`SchedulerConfig` unchanged.
    """

    policy: str = "least_loaded"
    max_fleet_queue_depth: Optional[int] = None
    shed_priority_floor: int = 1

    def __post_init__(self):
        if self.policy not in ("least_loaded", "round_robin"):
            raise ValueError(f"policy must be least_loaded|round_robin, got {self.policy!r}")
        if self.max_fleet_queue_depth is not None and self.max_fleet_queue_depth < 1:
            raise ValueError(
                f"max_fleet_queue_depth must be >= 1, got {self.max_fleet_queue_depth}"
            )


class FleetRoutingPolicy:
    """Replica-selection + fleet-admission decisions for a
    :class:`~accelerate_tpu.serving_fleet.FleetRouter` — the same
    policy/mechanism split as :class:`Scheduler`: all replica state stays
    in the router, this object only decides."""

    def __init__(self, config: Optional[RoutingConfig] = None):
        self.config = config or RoutingConfig()
        self._rr = 0

    def shed_on_submit(self, priority: int, fleet_queue_depth: int) -> Optional[str]:
        """Reason string if a new request must be rejected at the fleet
        edge (aggregate queue-depth SLO; priority classes below the shed
        floor are never rejected)."""
        cfg = self.config
        if cfg.max_fleet_queue_depth is None or priority < cfg.shed_priority_floor:
            return None
        if fleet_queue_depth >= cfg.max_fleet_queue_depth:
            return (
                f"fleet queue depth {fleet_queue_depth} >= "
                f"max_fleet_queue_depth {cfg.max_fleet_queue_depth}"
            )
        return None

    def shed_on_capacity(self, n_routable: int) -> Optional[str]:
        """Reason string if the fleet has NO routable capacity left (every
        replica quarantined/dead/draining) — the circuit-breaker edge: a
        submission that cannot be served anywhere is rejected with a
        structured :class:`ShedError` instead of queueing into a black
        hole. Unlike depth shedding this ignores the priority floor: no
        class is servable when nothing is serving."""
        if n_routable <= 0:
            return "no serving replicas (fleet capacity lost)"
        return None

    def pick_replica(self, loads: Sequence[float], eligible: Sequence[int]) -> int:
        """Index (into ``loads``) of the replica a request should route
        to, among ``eligible`` indices. ``loads`` is queued + active per
        replica."""
        if not eligible:
            raise ValueError("no eligible replicas")
        if self.config.policy == "round_robin":
            pick = sorted(eligible)[self._rr % len(eligible)]
            self._rr += 1
            return pick
        return min(eligible, key=lambda i: (loads[i], i))


class Scheduler:
    """Decision surface the engine consults every tick. Stateless beyond
    its config — all request/slot state stays in the engine, so the
    policy is trivially swappable (subclass and override a method)."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()

    # ---- ordering -----------------------------------------------------

    def order_key(self, priority: int, uid: int) -> tuple:
        """Queue position: priority class first (lower admits sooner),
        submission order within a class. FIFO mode ignores priority."""
        if self.config.mode == "fifo":
            return (0, uid)
        return (int(priority), uid)

    # ---- token budget -------------------------------------------------

    def tick_budget(self, n_decoding: int, tick_block: int) -> float:
        """Prefill-token budget for this tick after active decodes claim
        theirs. ``inf`` when budgeting is off (fifo / no budget)."""
        if self.config.mode == "fifo" or self.config.token_budget is None:
            return math.inf
        return max(0, self.config.token_budget - n_decoding * tick_block)

    # ---- SLO shedding -------------------------------------------------

    def sheddable(self, priority: int) -> bool:
        return self.config.mode != "fifo" and priority >= self.config.shed_priority_floor

    def shed_on_submit(self, priority: int, queue_depth: int) -> Optional[str]:
        """Reason string if a new request must be rejected at submit."""
        cfg = self.config
        if cfg.max_queue_depth is None or not self.sheddable(priority):
            return None
        if queue_depth >= cfg.max_queue_depth:
            return f"queue depth {queue_depth} >= max_queue_depth {cfg.max_queue_depth}"
        return None

    def shed_on_wait(self, priority: int, wait_s: float) -> Optional[str]:
        """Reason string if a queued request has blown the wait SLO."""
        cfg = self.config
        if cfg.max_queue_wait_s is None or not self.sheddable(priority):
            return None
        if wait_s > cfg.max_queue_wait_s:
            return f"queue wait {wait_s:.3f}s > max_queue_wait_s {cfg.max_queue_wait_s}"
        return None

    # ---- preemption ---------------------------------------------------

    def pick_victim(self, incoming_priority: int, decoding: list) -> Optional[int]:
        """Slot to evict so a more important request can admit, or None.

        ``decoding``: ``[(slot, priority, uid), ...]`` for slots
        currently in the decode phase. The victim is the *least
        important, youngest* decode (max ``(priority, uid)``) — and only
        if it is both sheddable by the preemption floor and strictly
        less important than the incoming request, so equal-priority
        traffic never churns itself.
        """
        if self.config.mode == "fifo" or not self.config.enable_preemption:
            return None
        candidates = [
            (prio, uid, slot)
            for slot, prio, uid in decoding
            if prio >= self.config.preempt_priority_floor and prio > incoming_priority
        ]
        if not candidates:
            return None
        return max(candidates)[2]

    # ---- speculative gating -------------------------------------------

    def use_speculative(self, decoding_priorities) -> bool:
        """Whether this tick's decode pass may run the speculative tick
        (only consulted when the engine has a draft model)."""
        allowed = self.config.speculative_priorities
        if allowed is None:
            return True
        return all(p in allowed for p in decoding_priorities)
