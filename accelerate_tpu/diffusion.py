"""Diffusion schedule, training loss, and jitted DDPM/DDIM samplers.

Reference analogue: the reference generates images by driving a diffusers
pipeline under ``PartialState`` process splits
(reference: examples/inference/distributed/stable_diffusion.py,
distributed_image_generation.py); the pipeline internals live in the
diffusers package. Here the whole loop is in-tree and TPU-shaped:

* the noise schedule is a small pytree of precomputed arrays (no Python
  objects in the hot loop);
* sampling is ONE ``lax.scan`` over denoising steps inside one jit —
  static shapes, no per-step dispatch (the generation.py design applied
  to diffusion);
* ``sample`` is mesh-aware exactly like ``generate``: a model sharded by
  :func:`~accelerate_tpu.big_modeling.shard_model` (or prepared by the
  Accelerator) denoises with its params sharded and the image batch over
  the ``data`` axes.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np


def _jax():
    import jax

    return jax


def make_schedule(num_train_steps: int = 1000, beta_start: float = 1e-4, beta_end: float = 0.02, kind: str = "linear"):
    """Precompute the DDPM noise schedule as a dict of [T] arrays."""
    if kind == "linear":
        betas = np.linspace(beta_start, beta_end, num_train_steps, dtype=np.float64)
    elif kind == "cosine":  # Nichol & Dhariwal
        s = 0.008
        t = np.arange(num_train_steps + 1, dtype=np.float64) / num_train_steps
        f = np.cos((t + s) / (1 + s) * math.pi / 2) ** 2
        betas = np.clip(1 - f[1:] / f[:-1], 0, 0.999)
    else:
        raise ValueError(f"kind must be linear|cosine, got {kind!r}")
    alphas = 1.0 - betas
    alphas_bar = np.cumprod(alphas)
    return {
        "betas": betas.astype(np.float32),
        "alphas": alphas.astype(np.float32),
        "alphas_bar": alphas_bar.astype(np.float32),
        "sqrt_alphas_bar": np.sqrt(alphas_bar).astype(np.float32),
        "sqrt_one_minus_alphas_bar": np.sqrt(1.0 - alphas_bar).astype(np.float32),
        "num_train_steps": num_train_steps,
    }


def _add_noise(schedule, x0, rng):
    """Forward-noise ``x0`` at a uniform random timestep per sample:
    returns ``(x_t, t, noise)`` — the shared front half of every
    noise-prediction objective."""
    jax = _jax()
    jnp = jax.numpy
    t_key, n_key = jax.random.split(rng)
    t = jax.random.randint(t_key, (x0.shape[0],), 0, schedule["num_train_steps"])
    noise = jax.random.normal(n_key, x0.shape, x0.dtype)
    sab = jnp.asarray(schedule["sqrt_alphas_bar"])[t][:, None, None, None]
    somab = jnp.asarray(schedule["sqrt_one_minus_alphas_bar"])[t][:, None, None, None]
    return sab * x0 + somab * noise, t, noise


def diffusion_loss(params, batch, apply_fn, schedule, rng):
    """Noise-prediction MSE (DDPM simple loss): sample t ~ U, add noise,
    predict it. ``batch = {"images": [B,H,W,C](, "labels": [B])}``. Use
    with ``build_train_step`` via a closure over (apply_fn, schedule) —
    the rng argument receives the step's folded key."""
    jax = _jax()
    jnp = jax.numpy
    x_t, t, noise = _add_noise(schedule, batch["images"], rng)
    pred = apply_fn(params, x_t, t, batch.get("labels"))
    return jnp.mean((pred.astype(jnp.float32) - noise.astype(jnp.float32)) ** 2)


def sample(
    model,
    batch_size: int,
    num_steps: int = 50,
    schedule=None,
    method: str = "ddim",
    eta: float = 0.0,
    class_labels=None,
    guidance_scale: Optional[float] = None,
    seed: int = 0,
    encoder_hidden_states=None,
    uncond_hidden_states=None,
):
    """Generate ``[B, H, W, C]`` images with a jitted denoising scan.

    ``method="ddim"`` (deterministic when ``eta=0``) or ``"ddpm"``
    (ancestral, uses the full posterior variance). ``guidance_scale``
    enables classifier-free guidance; the null branch is the reserved
    LAST class id (class-conditional models) or ``uncond_hidden_states``
    (text-conditional models — the empty-prompt encoding, zeros when
    omitted); each step runs the denoiser on both and extrapolates.
    Text-conditional models (``config.context_dim``) condition every step
    on ``encoder_hidden_states`` [B, T, D].
    """
    jax = _jax()
    jnp = jax.numpy

    schedule = schedule or make_schedule()
    cfg = model.config
    shape = (batch_size, cfg.sample_size, cfg.sample_size, cfg.out_channels)
    T = schedule["num_train_steps"]
    if not 1 <= num_steps <= T:
        raise ValueError(f"num_steps must be in [1, {T}], got {num_steps}")
    if method not in ("ddim", "ddpm"):
        raise ValueError(f"method must be ddim|ddpm, got {method!r}")
    text_conditional = getattr(cfg, "context_dim", None) is not None
    if text_conditional and encoder_hidden_states is None:
        raise ValueError("text-conditional UNet needs encoder_hidden_states")
    if guidance_scale is not None and cfg.num_classes is None and not text_conditional:
        raise ValueError("guidance needs a class-conditional or text-conditional UNet")
    # evenly spaced timestep subsequence, descending
    ts = np.linspace(0, T - 1, num_steps).round().astype(np.int32)[::-1].copy()
    ts_prev = np.concatenate([ts[1:], [-1]]).astype(np.int32)

    from .generation import _params_mesh, _trace_ctx

    mesh = _params_mesh(model.params)

    labels = None
    if cfg.num_classes is not None:
        if class_labels is None:
            raise ValueError("class-conditional UNet needs class_labels")
        labels = jnp.asarray(class_labels, jnp.int32)

    ctx = uctx = None
    if text_conditional:
        ctx = jnp.asarray(encoder_hidden_states)
        if guidance_scale is not None:
            uctx = jnp.zeros_like(ctx) if uncond_hidden_states is None else jnp.asarray(uncond_hidden_states)

    # the schedule's arrays are closure-captured by the jitted runner, so
    # its CONTENT must be part of the cache key — a different schedule with
    # the same shape would otherwise silently reuse the old constants
    import hashlib

    sched_key = (T, hashlib.sha1(np.asarray(schedule["alphas_bar"]).tobytes()).hexdigest()[:12])
    ctx_key = None if ctx is None else ctx.shape
    cache_key = ("diffusion", batch_size, num_steps, method, float(eta), guidance_scale,
                 sched_key, ctx_key, None if mesh is None else tuple(sorted(mesh.shape.items())))
    runners = model.__dict__.setdefault("_generate_runners", {})

    ab = jnp.asarray(schedule["alphas_bar"])

    def apply(params, x, t_b, labels, ctx):
        if text_conditional:
            return model.apply_fn(params, x, t_b, labels, encoder_hidden_states=ctx)
        return model.apply_fn(params, x, t_b, labels)

    def denoise(params, x, t_b, labels, ctx, uctx):
        if guidance_scale is None:
            return apply(params, x, t_b, labels, ctx)
        both = jnp.concatenate([x, x])
        t2 = jnp.concatenate([t_b, t_b])
        lab2 = None
        if labels is not None:
            null = jnp.full_like(labels, cfg.num_classes - 1)
            lab2 = jnp.concatenate([labels, null])
        ctx2 = None if ctx is None else jnp.concatenate([ctx, uctx])
        eps = apply(params, both, t2, lab2, ctx2)
        cond, uncond = jnp.split(eps, 2)
        return uncond + guidance_scale * (cond - uncond)

    def run(params, labels, ctx, uctx, key):
        x = jax.random.normal(key, shape, jnp.float32)

        def step(carry, t_pair):
            x, key = carry
            t, t_prev = t_pair
            t_b = jnp.full((batch_size,), t, jnp.int32)
            eps = denoise(params, x, t_b, labels, ctx, uctx).astype(jnp.float32)
            a_t = ab[t]
            a_prev = jnp.where(t_prev >= 0, ab[jnp.maximum(t_prev, 0)], 1.0)
            x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
            x0 = jnp.clip(x0, -4.0, 4.0)  # mild stabilisation, standard practice
            key, sub = jax.random.split(key)
            if method == "ddim":
                sigma = eta * jnp.sqrt((1 - a_prev) / (1 - a_t)) * jnp.sqrt(1 - a_t / a_prev)
            else:  # ddpm ancestral
                sigma = jnp.sqrt((1 - a_prev) / (1 - a_t) * (1 - a_t / a_prev))
            dir_xt = jnp.sqrt(jnp.maximum(1.0 - a_prev - sigma**2, 0.0)) * eps
            noise = jnp.where(t_prev >= 0, 1.0, 0.0) * sigma * jax.random.normal(sub, shape)
            x = jnp.sqrt(a_prev) * x0 + dir_xt + noise
            return (x, key), None

        (x, _), _ = jax.lax.scan(step, (x, key), (jnp.asarray(ts), jnp.asarray(ts_prev)))
        return x

    if cache_key in runners:
        with _trace_ctx(mesh):
            return runners[cache_key](model.params, labels, ctx, uctx, jax.random.key(seed))

    jitted = jax.jit(run)
    with _trace_ctx(mesh):
        out = jitted(model.params, labels, ctx, uctx, jax.random.key(seed))
    runners[cache_key] = jitted
    return out


def latent_diffusion_loss(
    params,
    batch,
    apply_fn,
    schedule,
    rng,
    *,
    vae,
    vae_params=None,
    text_encoder=None,
    text_params=None,
    cond_drop_prob: float = 0.1,
):
    """Noise-prediction MSE in VAE latent space (the stable-diffusion
    training objective — reference pipelines train this inside diffusers;
    here it is one pure function fit for ``build_train_step``).

    ``params`` are the UNet's (the only trainable tree); the VAE and text
    encoder are frozen conditioning machinery (``stop_gradient``).
    ``batch = {"pixel_values": [B,H,W,C], "encoder_hidden_states": [B,T,D]}``
    or with ``input_ids`` + ``text_encoder``/``text_params`` to encode
    in-step. ``cond_drop_prob`` zeroes the conditioning per-sample so the
    model learns the unconditional branch classifier-free guidance needs.
    """
    jax = _jax()
    jnp = jax.numpy
    enc_key, noise_key, drop_key = jax.random.split(rng, 3)

    latents, _, _ = vae.encode_fn(vae.params if vae_params is None else vae_params, batch["pixel_values"], enc_key)
    latents = jax.lax.stop_gradient(latents.astype(jnp.float32))

    ctx = batch.get("encoder_hidden_states")
    if ctx is None:
        if text_encoder is None:
            raise ValueError("need encoder_hidden_states in the batch or a text_encoder")
        ctx = text_encoder(text_params, batch["input_ids"])
    ctx = jax.lax.stop_gradient(ctx)
    if cond_drop_prob > 0.0:
        keep = jax.random.bernoulli(drop_key, 1.0 - cond_drop_prob, (latents.shape[0],))
        ctx = jnp.where(keep[:, None, None], ctx, jnp.zeros_like(ctx))

    z_t, t, noise = _add_noise(schedule, latents, noise_key)
    pred = apply_fn(params, z_t, t, None, ctx)
    return jnp.mean((pred.astype(jnp.float32) - noise.astype(jnp.float32)) ** 2)


def text_to_image(
    unet,
    vae,
    text_model,
    prompt_ids,
    uncond_ids=None,
    guidance_scale: Optional[float] = 7.5,
    num_steps: int = 50,
    schedule=None,
    method: str = "ddim",
    eta: float = 0.0,
    seed: int = 0,
):
    """Prompts → images: encode text, denoise latents under
    classifier-free guidance, decode with the VAE.

    The in-tree equivalent of the reference's flagship diffusion example
    (reference: examples/inference/distributed/stable_diffusion.py — a
    diffusers ``StableDiffusionPipeline`` driven under process splits).
    Data-parallel prompt fan-out composes the same way there as here:
    split ``prompt_ids`` between processes/``data`` axis.

    ``text_model`` is a CLIP-family Model exposing
    ``encode_text(params, ids) -> [B,T,D]`` (``models/clip.py``);
    ``uncond_ids`` is the tokenized empty prompt (zeros when omitted —
    training's dropped-conditioning token).
    """
    jax = _jax()
    jnp = jax.numpy

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    if prompt_ids.ndim == 1:  # single unbatched prompt
        prompt_ids = prompt_ids[None]
    ctx = text_model.encode_text(text_model.params, prompt_ids)
    uctx = None
    if guidance_scale is not None:
        if uncond_ids is None:
            uctx = jnp.zeros_like(ctx)
        else:
            uncond_ids = jnp.asarray(uncond_ids, jnp.int32)
            if uncond_ids.ndim == 1:
                uncond_ids = jnp.broadcast_to(uncond_ids[None], prompt_ids.shape)
            uctx = text_model.encode_text(text_model.params, uncond_ids)

    latents = sample(
        unet,
        batch_size=prompt_ids.shape[0],
        num_steps=num_steps,
        schedule=schedule,
        method=method,
        eta=eta,
        guidance_scale=guidance_scale,
        seed=seed,
        encoder_hidden_states=ctx,
        uncond_hidden_states=uctx,
    )
    return vae.decode_fn(vae.params, latents)
