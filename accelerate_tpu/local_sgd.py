"""LocalSGD — train independent data-parallel replicas, average periodically.

Reference analogue: src/accelerate/local_sgd.py (106 LoC): a context manager
that skips DDP gradient sync for ``local_sgd_steps`` steps, then averages
model parameters across ranks (``_sync_and_avg_model_params``,
local_sgd.py:98).

TPU-native design. Under SPMD a replicated parameter cannot diverge per
device, so "skip the sync" is not expressible on replicated params. Instead
each data-parallel replica gets its *own* parameter copy: params are stacked
along a new leading axis of size ``dp`` that is sharded over the mesh
``data`` axis, and the local step is a ``vmap`` over that axis — XLA compiles
it with **zero cross-replica collectives** (the point of LocalSGD: no psum
per step, which matters when the data axis rides DCN, not ICI). Every
``local_sgd_steps`` steps (and on context exit) a second jitted program
averages the stack and re-broadcasts it.

Usage (API mirrors the reference)::

    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=8) as lsgd:
        step = lsgd.build_local_step(loss_fn)
        for batch in dl:
            loss = step(batch)   # no cross-replica comms
            lsgd.step()          # averages params every 8 calls
"""

from __future__ import annotations

from typing import Callable


def _jax():
    import jax

    return jax


class LocalSGD:
    """(reference: local_sgd.py:19). ``enabled=False`` or a trivial data
    axis degrades to a no-op wrapper, like the reference outside
    multi-GPU."""

    def __init__(self, accelerator, model=None, local_sgd_steps: int = 8, enabled: bool = True):
        self.accelerator = accelerator
        self.model = model if model is not None else (accelerator._models[-1] if accelerator._models else None)
        self.local_sgd_steps = local_sgd_steps
        self.dp = accelerator.num_data_shards
        self.enabled = enabled and self.dp > 1
        self.num_steps = 0
        self._stacked = None  # (params, opt_state) stacks, set on __enter__
        self._optimizer = None
        self._local_step = None
        self._sync_step = None

    # -- context manager (reference: local_sgd.py:61-82) ------------------- #

    def __enter__(self):
        if self.enabled:
            self.num_steps = 0
        return self

    def __exit__(self, *exc):
        if self.enabled and self._stacked is not None:
            self._sync_and_avg_model_params()
            self._write_back()

    def step(self):
        """Count one optimizer step; average replicas on the boundary
        (reference: local_sgd.py:83-96)."""
        self.num_steps += 1
        if not self.enabled:
            return
        if self.num_steps % self.local_sgd_steps == 0:
            self._sync_and_avg_model_params()

    # -- the vmapped local step -------------------------------------------- #

    def build_local_step(self, loss_fn: Callable, optimizer=None) -> Callable:
        """Build ``step(batch) -> per_replica_losses`` updating ``dp``
        independent replicas with no cross-replica communication (reduce the
        returned ``(dp,)`` loss vector yourself when you actually read it).

        ``loss_fn(params, batch) -> loss``. ``batch`` leaves must have a
        leading global batch dimension divisible by ``dp``; each replica
        sees its own ``1/dp`` slice (which is exactly the shard already
        resident on its devices when the batch is data-sharded).
        """
        jax = _jax()
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        acc = self.accelerator
        if self.model is None:
            raise ValueError("LocalSGD needs a prepared model")
        optimizer = optimizer or (acc._optimizers[-1] if acc._optimizers else None)
        if optimizer is None:
            raise ValueError("prepare() an optimizer before build_local_step")
        self._optimizer = optimizer
        tx = getattr(optimizer, "optimizer", optimizer)
        dp = self.dp

        if not self.enabled:
            # degrade to the accelerator's normal (globally synced) step
            return acc.build_train_step(loss_fn, model=self.model, optimizer=optimizer)

        mesh = acc.mesh
        stack_shard = NamedSharding(mesh, P("data"))

        def stack(p):
            return jax.device_put(jnp.broadcast_to(p[None], (dp, *p.shape)), stack_shard)

        params_stacked = jax.tree_util.tree_map(stack, self.model.params)
        # carry the prepared optimizer's REAL state into the replicas
        # (accumulated moments, step count) — re-initialising here would
        # spike Adam's bias correction mid-run and reset count-keyed LR
        # schedules on exit; the reference leaves optimizer state untouched
        acc._ensure_opt_state(optimizer, self.model)
        opt_stacked = jax.tree_util.tree_map(stack, optimizer.opt_state)
        self._stacked = [params_stacked, opt_stacked]

        import optax

        def one_replica(params, opt_state, microbatch):
            loss, grads = jax.value_and_grad(loss_fn)(params, microbatch)
            updates, new_opt = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt, loss

        @jax.jit
        def local_step(params_stacked, opt_stacked, batch):
            micro = jax.tree_util.tree_map(lambda x: x.reshape(dp, x.shape[0] // dp, *x.shape[1:]), batch)
            # per-replica losses are returned unreduced so the hot program
            # stays 100% collective-free; the mean happens outside
            return jax.vmap(one_replica)(params_stacked, opt_stacked, micro)

        @jax.jit
        def sync_step(params_stacked):
            return jax.tree_util.tree_map(
                lambda p: jnp.broadcast_to(p.mean(axis=0, keepdims=True), p.shape), params_stacked
            )

        self._local_step = local_step
        self._sync_step = sync_step

        def step(batch):
            p, o, losses = local_step(self._stacked[0], self._stacked[1], batch)
            self._stacked[0], self._stacked[1] = p, o
            # per-replica loss vector, unreduced: reading/reducing it is the
            # caller's choice — keeping the hot path free of cross-replica
            # traffic is the whole point of LocalSGD
            return losses

        return step

    # -- averaging (reference: local_sgd.py:98-106) ------------------------ #

    def _sync_and_avg_model_params(self):
        if self._stacked is None:
            return
        self.accelerator.wait_for_everyone()
        self._stacked[0] = self._sync_step(self._stacked[0])

    def _write_back(self):
        """Collapse the replica stacks back into the model's (replicated)
        params and the prepared optimizer's state on exit, so training can
        continue (or checkpoint) seamlessly after the LocalSGD block."""
        jax = _jax()
        import jax.numpy as jnp

        def restore_sharding(n, o):
            return jax.device_put(n, o.sharding) if hasattr(o, "sharding") else n

        new_params = jax.tree_util.tree_map(lambda p: p[0], self._stacked[0])
        old = self.model.params
        self.model.params = jax.tree_util.tree_map(restore_sharding, new_params, old)
        if self._optimizer is not None and getattr(self._optimizer, "opt_state", None) is not None:
            # float moments: replica mean (params were just averaged, so the
            # matching state is the averaged one); ints (step counts): any
            # replica — they are identical.
            def collapse(s):
                if hasattr(s, "dtype") and jnp.issubdtype(s.dtype, jnp.floating):
                    return s.mean(axis=0)
                return s[0] if hasattr(s, "shape") and s.ndim > 0 else s

            new_opt = jax.tree_util.tree_map(collapse, self._stacked[1])
            self._optimizer.opt_state = jax.tree_util.tree_map(
                restore_sharding, new_opt, self._optimizer.opt_state
            )
        self._stacked = None

    @property
    def replica_params(self):
        """The live ``(dp, ...)`` parameter stack (diagnostics/tests)."""
        return self._stacked[0] if self._stacked is not None else None
