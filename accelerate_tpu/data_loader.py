"""Data loading: host-local reads assembled into *global* sharded arrays.

Reference analogue: src/accelerate/data_loader.py (1447 LoC). The reference
has two sharding modes — shard-the-sampler (``DataLoaderShard`` :500 +
``BatchSamplerShard`` :110) and dispatch-from-rank-0 (``DataLoaderDispatcher``
:704) — plus an XLA wrapper (``MpDeviceLoaderWrapper`` :654). Here both modes
produce the same thing: a pytree of **global ``jax.Array``s whose batch dim
is sharded over the mesh batch axes** (``data``×``fsdp``), built with
``jax.make_array_from_process_local_data``. A jitted step consumes them with
zero re-layout.

Key behaviors preserved (and their reference anchors):

* per-shard ``batch_size`` semantics and ``split_batches``
  (data_loader.py:996 ``prepare_data_loader`` args);
* seedable, cross-process-identical shuffling (``SeedableRandomSampler``
  :73) via a seed+epoch-derived ``numpy`` Generator — every host computes
  the same permutation, no RNG broadcast needed;
* fetch-ahead-one iteration so ``end_of_dataloader``/``remainder`` are set
  *before* the last batch is yielded (:558-592, :365-405);
* ``even_batches`` wrap-around padding of the final batch with
  ``GradientState.remainder`` bookkeeping driving ``gather_for_metrics``
  truncation (:878-916);
* ``skip_first_batches`` for checkpoint resume (:1371).

Static-shape note (TPU-specific): uneven final batches are *padded, never
ragged* — a ragged batch would retrigger XLA compilation. ``even_batches=
False`` pads to the next multiple of the data-shard count instead of going
ragged, with the mask carried by ``remainder``.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Iterable, Optional

import numpy as np

from .logging import get_logger
from .state import GradientState
from .utils.dataclasses import DataLoaderConfiguration
from .utils.random import synchronize_rng_states

logger = get_logger(__name__)


def _jax():
    import jax

    return jax


def _to_numpy(x):
    if hasattr(x, "detach"):  # torch tensor (optional interop)
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def default_collate(samples: list) -> Any:
    """Stack a list of samples into a batch pytree of numpy arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    return np.stack([_to_numpy(s) for s in samples])


class SeedableRandomSampler:
    """Cross-process reproducible permutation sampler
    (reference: data_loader.py:73). The permutation is a pure function of
    ``seed + epoch`` so every host computes the same order."""

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()

    def __len__(self):
        return self.data_source_len


class SequentialSampler:
    def __init__(self, data_source_len: int):
        self.data_source_len = data_source_len

    def set_epoch(self, epoch: int):
        pass

    def __iter__(self):
        yield from range(self.data_source_len)

    def __len__(self):
        return self.data_source_len


class BaseDataLoader:
    """Shared bookkeeping: GradientState registration, remainder tracking,
    device placement of global batches."""

    def __init__(
        self,
        *,
        batch_sharding=None,
        device_placement: bool = True,
        rng_types: Optional[list] = None,
        generator=None,
        prefetch_size: int = 2,
        auto_bucketing: bool = False,
    ):
        self.gradient_state = GradientState()
        self.batch_sharding_ = batch_sharding
        self.device_placement = device_placement
        self.rng_types = rng_types
        self.generator = generator
        self.prefetch_size = max(1, prefetch_size)
        self.auto_bucketing = auto_bucketing
        self.bucketer = None  # created lazily (needs the live shard count)
        self.end_of_dataloader = False
        self.remainder = -1
        self.iteration = 0
        self.skip_batches = 0
        self.batches_yielded = 0
        self._is_accelerate_prepared = True

    def _mesh_sharding(self):
        if self.batch_sharding_ is not None:
            return self.batch_sharding_
        from .state import AcceleratorState

        state = AcceleratorState._shared_state
        if state.get("_initialized") and state.get("mesh") is not None:
            from .parallel.mesh import batch_sharding

            self.batch_sharding_ = batch_sharding(state["mesh"])
        return self.batch_sharding_

    def _num_shards(self) -> int:
        sharding = self._mesh_sharding()
        if sharding is None:
            return 1
        from .parallel.mesh import data_parallel_size

        return data_parallel_size(sharding.mesh)

    def _bucket_pad(self, host_batch, global_len: int):
        """Auto-bucketing (``DataLoaderConfiguration(auto_bucketing=True)``;
        see :mod:`accelerate_tpu.aot.bucketing`): wrap-pad the host batch's
        rows so the GLOBAL batch dim lands on a learned bucket instead of
        whatever ragged size the tail (or a variable stream) produced — a
        stream of ragged shapes then compiles at most ``len(buckets)``
        programs and the recompile watchdog stays silent after warmup.
        Padded rows repeat from the batch start (the ``even_batches`` tail
        semantics), and the caller's ``remainder`` bookkeeping truncates
        them in ``gather_for_metrics`` exactly as for an evened tail.
        Returns ``(host_batch, padded_global_len)``."""
        if not self.auto_bucketing or global_len == 0:
            return host_batch, global_len
        if self.bucketer is None:
            import math

            from .aot.bucketing import ShapeBucketer

            jax = _jax()
            # buckets must split over BOTH the mesh batch axes and the
            # process-local slices; seeding with the steady global batch
            # keeps full batches bucket-exact (zero pad in steady state)
            mult = math.lcm(max(1, self._num_shards()), max(1, jax.process_count()))
            seed = [self.total_batch_size] if getattr(self, "total_batch_size", 0) else []
            self.bucketer = ShapeBucketer(seed, multiple_of=mult)
        target = self.bucketer.bucket(global_len)
        if target == global_len:
            return host_batch, global_len
        from .aot.bucketing import pad_batch_tree

        jax = _jax()
        pc = 1 if getattr(self, "_dispatch_source", False) else jax.process_count()
        host_batch = pad_batch_tree(host_batch, target // pc, current=global_len // pc)
        return host_batch, target

    def _place(self, host_batch):
        """per-host numpy batch -> global sharded jax.Array pytree."""
        if not self.device_placement:
            return host_batch
        sharding = self._mesh_sharding()
        jax = _jax()
        if sharding is None:
            return jax.device_put(host_batch)

        def make(x):
            x = _to_numpy(x)
            return jax.make_array_from_process_local_data(sharding, x)

        return jax.tree_util.tree_map(make, host_batch)

    def begin(self):
        """(reference: data_loader.py:365) reset + register with GradientState."""
        self.end_of_dataloader = False
        self.remainder = -1
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)

    def set_epoch(self, epoch: int):
        self.iteration = epoch
        if hasattr(self, "sampler") and hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)
        if hasattr(self, "dataset") and hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def state_dict(self) -> dict:
        """Exact position for mid-epoch resume (reference analogue:
        StatefulDataLoader state dicts persisted at checkpointing.py:139-143).
        ``batches_yielded`` counts batches delivered this epoch; restoring
        replays the same sampler permutation and skips exactly that many.
        ``global_batch_size``/``data_parallel_degree`` record what one
        counted batch *meant* on the saving topology, so an elastic
        restore (``ft.topology.redistribute_sampler_state``) can convert
        the position into a global sample offset and re-split it across a
        different data-parallel degree."""
        sampler = getattr(self, "sampler", None)
        return {
            "iteration": self.iteration,
            "batches_yielded": self.batches_yielded,
            "sampler_epoch": getattr(sampler, "epoch", None),
            "sampler_seed": getattr(sampler, "seed", None),
            "global_batch_size": getattr(self, "total_batch_size", None),
            "data_parallel_degree": self._num_shards(),
        }

    def load_state_dict(self, state: dict):
        self.iteration = state.get("iteration", 0)
        self.batches_yielded = state.get("batches_yielded", 0)
        # resume position: the next iteration skips the delivered batches
        self.skip_batches = self.batches_yielded
        sampler = getattr(self, "sampler", None)
        if sampler is not None:
            if state.get("sampler_seed") is not None and hasattr(sampler, "seed"):
                sampler.seed = state["sampler_seed"]
            if state.get("sampler_epoch") is not None and hasattr(sampler, "set_epoch"):
                sampler.set_epoch(state["sampler_epoch"])


class DataLoaderShard(BaseDataLoader):
    """Map-style loader: every host samples the same global index order and
    reads only the rows destined for its local devices
    (reference: data_loader.py:500 + BatchSamplerShard :110).

    ``batch_size`` is per data-shard (matching the reference's per-process
    meaning); the global batch is ``batch_size * num_data_shards`` unless
    ``split_batches``.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        seed: int = 0,
        collate_fn: Optional[Callable] = None,
        drop_last: bool = False,
        even_batches: bool = True,
        split_batches: bool = False,
        sampler=None,
        rng_types: Optional[list] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.even_batches = even_batches
        self.split_batches = split_batches
        self.rng_types = rng_types
        if sampler is None:
            sampler = SeedableRandomSampler(len(dataset), seed=seed) if shuffle else SequentialSampler(len(dataset))
        self.sampler = sampler

    @property
    def total_batch_size(self) -> int:
        """Global batch size (reference: data_loader.py:612)."""
        n = self._num_shards()
        return self.batch_size if self.split_batches else self.batch_size * n

    @property
    def total_dataset_length(self) -> int:
        return len(self.dataset)

    def __len__(self):
        g = self.total_batch_size
        n = len(self.dataset) - self.skip_batches * g
        if self.drop_last:
            return max(0, n // g)
        return max(0, math.ceil(n / g))

    def _global_index_batches(self):
        indices = list(self.sampler)
        g = self.total_batch_size
        start = self.skip_batches * g
        for i in range(start, len(indices), g):
            chunk = indices[i : i + g]
            if len(chunk) < g:
                if self.drop_last:
                    return
                n_real = len(chunk)
                if self.even_batches:
                    # wrap-around pad to the full global batch
                    # (reference: data_loader.py:878-916)
                    while len(chunk) < g:
                        chunk += indices[: g - len(chunk)]
                else:
                    # pad minimally to a multiple of the shard count —
                    # never ragged (static shapes; see module docstring)
                    n = self._num_shards()
                    target = math.ceil(len(chunk) / n) * n
                    while len(chunk) < target:
                        chunk += indices[: target - len(chunk)]
                yield chunk, n_real
                return
            yield chunk, len(chunk)

    def _local_rows(self, index_batch: list) -> list:
        if getattr(self, "_dispatch_source", False):
            # dispatch mode: process 0 reads the FULL global batch; the
            # dispatcher scatters per-process slices afterwards
            return index_batch
        jax = _jax()
        pc, pi = jax.process_count(), jax.process_index()
        if pc == 1:
            return index_batch
        rows = len(index_batch) // pc
        return index_batch[pi * rows : (pi + 1) * rows]

    def _load(self, index_batch: list):
        samples = [self.dataset[i] for i in self._local_rows(index_batch)]
        return self.collate_fn(samples)

    def __iter__(self):
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.generator)
        self.begin()
        # batches_yielded continues from skip_batches so a resumed epoch's
        # position counter matches an uninterrupted run's
        self.batches_yielded = self.skip_batches
        completed = False
        try:
            # Prefetch window: device transfers (device_put is async) are
            # scheduled ``prefetch_size`` batches ahead, overlapping host
            # collate with device compute. Fetch-ahead also guarantees
            # end_of_dataloader/remainder are set *before* the last batch
            # is yielded (reference :558-592).
            window: deque = deque()
            for idx_batch, n_real in self._global_index_batches():
                host, padded = self._bucket_pad(self._load(idx_batch), len(idx_batch))
                window.append((self._place(host), n_real, padded))
                if len(window) > self.prefetch_size:
                    self.batches_yielded += 1
                    yield window.popleft()[0]
            while window:
                batch, n_real, padded = window.popleft()
                if not window:
                    self.end_of_dataloader = True
                    self.remainder = n_real if n_real != padded else -1
                self.batches_yielded += 1
                yield batch
            completed = True
        finally:
            self.skip_batches = 0
            if completed:
                # advance the epoch only on a full pass (torch semantics);
                # on early break, iteration/sampler stay on the current
                # epoch so a subsequent state_dict() save stays consistent
                # with the recorded batches_yielded offset
                self.batches_yielded = 0
                self.iteration += 1
                if hasattr(self.sampler, "set_epoch"):
                    self.sampler.set_epoch(self.iteration)
            self.end()


class IterableDataLoaderShard(BaseDataLoader):
    """Iterable-dataset variant (reference: IterableDatasetShard,
    data_loader.py:266): stream samples, chunk into global batches; every
    process must iterate the same stream (or the dataset shards itself by
    ``jax.process_index()``)."""

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        collate_fn: Optional[Callable] = None,
        drop_last: bool = False,
        even_batches: bool = True,
        split_batches: bool = False,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.even_batches = even_batches
        self.split_batches = split_batches

    @property
    def total_batch_size(self) -> int:
        n = self._num_shards()
        return self.batch_size if self.split_batches else self.batch_size * n

    def _batched_samples(self):
        jax = _jax()
        if getattr(self, "_dispatch_source", False):
            # dispatch mode: process 0 consumes the whole stream and yields
            # FULL global batches; the dispatcher scatters per-process
            # slices afterwards (reference: data_loader.py:704-786 serves
            # IterableDataset through the dispatcher the same way)
            pc, pi = 1, 0
        else:
            pc, pi = jax.process_count(), jax.process_index()
        g = self.total_batch_size
        buf, first = [], []
        n_full = 0  # every full batch, skipped or yielded: the tail's ordinal
        for sample in self.dataset:
            buf.append(sample)
            if len(first) < g:
                first.append(sample)
            if len(buf) == g:
                n_full += 1
                if n_full <= self.skip_batches:
                    buf = []
                    continue
                local = buf[pi * (g // pc) : (pi + 1) * (g // pc)] if pc > 1 else buf
                yield self.collate_fn(local), g, g
                buf = []
        if buf and n_full < self.skip_batches:
            # the resume offset lands on (or past) the tail batch: it was
            # already delivered before the checkpoint, so don't replay it
            return
        if buf and not self.drop_last:
            n_real = len(buf)
            if self.even_batches:
                target = g
            else:
                n = self._num_shards()
                target = math.ceil(len(buf) / n) * n
            i = 0
            while len(buf) < target and first:
                buf.append(first[i % len(first)])
                i += 1
            local = buf[pi * (target // pc) : (pi + 1) * (target // pc)] if pc > 1 else buf
            yield self.collate_fn(local), n_real, target

    def __iter__(self):
        self.begin()
        self.batches_yielded = self.skip_batches
        completed = False
        try:
            window: deque = deque()
            for host_batch, n_real, padded in self._batched_samples():
                host_batch, padded = self._bucket_pad(host_batch, padded)
                window.append((self._place(host_batch), n_real, padded))
                if len(window) > self.prefetch_size:
                    self.batches_yielded += 1
                    yield window.popleft()[0]
            while window:
                batch, n_real, padded = window.popleft()
                if not window:
                    self.end_of_dataloader = True
                    # same contract as the map loader: REAL rows when the
                    # tail was padded, -1 when nothing needs truncating
                    self.remainder = n_real if n_real != padded else -1
                self.batches_yielded += 1
                yield batch
            completed = True
        finally:
            self.skip_batches = 0
            if completed:
                self.batches_yielded = 0
            self.end()


class DataLoaderDispatcher(BaseDataLoader):
    """Dispatch mode: process 0 reads every batch and broadcasts it over DCN
    (reference: data_loader.py:704, ``_fetch_batches`` :786-850). Useful when
    the dataset is only reachable from one host. Wraps either the map-style
    :class:`DataLoaderShard` or the streaming
    :class:`IterableDataLoaderShard` (reference serves IterableDataset
    through the same dispatcher, data_loader.py:704-786)."""

    def __init__(self, inner):
        super().__init__(
            batch_sharding=inner.batch_sharding_,
            device_placement=inner.device_placement,
            prefetch_size=inner.prefetch_size,
        )
        self.inner = inner
        # the inner loader runs host-unsharded on process 0 and reads the
        # full global batch (no per-process row slicing)
        self.inner.device_placement = False
        self.inner._dispatch_source = True

    @property
    def total_batch_size(self) -> int:
        return self.inner.total_batch_size

    @property
    def total_dataset_length(self) -> int:
        return self.inner.total_dataset_length

    def __len__(self):
        return len(self.inner)  # TypeError for an iterable inner, as for torch

    def set_epoch(self, epoch: int):
        self.inner.set_epoch(epoch)

    def state_dict(self) -> dict:
        state = self.inner.state_dict()
        state["batches_yielded"] = self.batches_yielded
        return state

    def load_state_dict(self, state: dict):
        self.inner.load_state_dict(state)
        self.batches_yielded = state.get("batches_yielded", 0)

    def __iter__(self):
        jax = _jax()
        pc, pi = jax.process_count(), jax.process_index()
        self.begin()
        self.batches_yielded = self.inner.skip_batches
        try:
            if pc == 1:
                for batch in self.inner:
                    self.end_of_dataloader = self.inner.end_of_dataloader
                    self.remainder = self.inner.remainder
                    self.batches_yielded += 1
                    yield self._place(batch)
                self.batches_yielded = 0
                return
            from .utils.operations import scatter_object

            it = iter(self.inner) if pi == 0 else None
            while True:
                payloads = None
                if pi == 0:
                    try:
                        batch = next(it)
                        full = jax.tree_util.tree_map(_to_numpy, batch)

                        # slice-before-send (reference: data_loader.py:786-850
                        # sends per-rank slices): each process receives only
                        # its own rows, never the full global batch
                        def rows_for(p):
                            def take(x):
                                r = x.shape[0] // pc
                                return x[p * r : (p + 1) * r]

                            return jax.tree_util.tree_map(take, full)

                        payloads = [
                            (rows_for(p), self.inner.end_of_dataloader, self.inner.remainder)
                            for p in range(pc)
                        ]
                    except StopIteration:
                        payloads = [None] * pc
                mine = scatter_object(payloads, from_process=0)
                if mine is None:
                    return
                local, end, rem = mine
                self.end_of_dataloader = end
                self.remainder = rem
                self.batches_yielded += 1
                yield self._place(local)
                if end:
                    self.batches_yielded = 0
        finally:
            # non-zero processes never run inner.__iter__, so the consumed
            # skip offset must be cleared here on every process
            self.inner.skip_batches = 0
            self.end()


def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    use_seedable_sampler: bool = True,
    seed: int = 0,
    data_loader_config: Optional[DataLoaderConfiguration] = None,
    batch_size: Optional[int] = None,
    shuffle: bool = False,
    collate_fn: Optional[Callable] = None,
    drop_last: bool = False,
):
    """Coerce a data source into a sharded loader
    (reference entry point: data_loader.py:996).

    Accepts: an already-prepared loader (idempotent, reference
    accelerator.py:1470-1475), a torch ``DataLoader`` (its dataset/batch
    size/collate/drop_last are lifted — torch never runs on device), any
    indexable dataset, or an iterable of samples.
    """
    if data_loader_config is not None:
        split_batches = data_loader_config.split_batches
        dispatch_batches = data_loader_config.dispatch_batches
        even_batches = data_loader_config.even_batches
        use_seedable_sampler = data_loader_config.use_seedable_sampler

    if isinstance(dataloader, BaseDataLoader):
        return dataloader

    # torch DataLoader interop: unwrap to its dataset + settings
    torch_loader = None
    try:  # soft dependency
        import torch.utils.data as tud

        if isinstance(dataloader, tud.DataLoader):
            torch_loader = dataloader
    except ImportError:
        pass

    if torch_loader is not None:
        dataset = torch_loader.dataset
        batch_size = torch_loader.batch_size if batch_size is None else batch_size
        drop_last = torch_loader.drop_last
        import torch.utils.data as tud

        shuffle = isinstance(getattr(torch_loader, "sampler", None), tud.RandomSampler)
        if torch_loader.collate_fn is not None and torch_loader.collate_fn is not tud.dataloader.default_collate:
            user_collate = torch_loader.collate_fn

            def collate_fn(samples):  # run torch collate, convert to numpy
                out = user_collate(samples)
                return _jax().tree_util.tree_map(_to_numpy, out)

        dataloader = dataset

    if batch_size is None:
        batch_size = 1

    common = dict(
        batch_size=batch_size,
        collate_fn=collate_fn,
        drop_last=drop_last,
        even_batches=even_batches,
        split_batches=split_batches,
        device_placement=put_on_device,
        prefetch_size=data_loader_config.prefetch_size if data_loader_config is not None else 2,
        auto_bucketing=data_loader_config.auto_bucketing if data_loader_config is not None else False,
    )

    if hasattr(dataloader, "__len__") and hasattr(dataloader, "__getitem__"):
        sampler = None
        if shuffle and not use_seedable_sampler:
            # draw one random seed but keep it identical on every host —
            # shuffling must stay cross-process consistent even when the
            # user opted out of the deterministic sampler
            from .utils.operations import broadcast_object_list

            random_seed = [int(np.random.randint(0, 2**31))]
            broadcast_object_list(random_seed, from_process=0)
            sampler = SeedableRandomSampler(len(dataloader), seed=random_seed[0])
        loader = DataLoaderShard(
            dataloader, shuffle=shuffle, seed=seed, sampler=sampler, rng_types=rng_types, **common
        )
    else:
        loader = IterableDataLoaderShard(dataloader, **common)

    if dispatch_batches:
        loader = DataLoaderDispatcher(loader)
    return loader


def skip_first_batches(dataloader, num_batches: int = 0):
    """Resume mid-epoch: skip the first ``num_batches`` of the next
    iteration (reference: data_loader.py:1371)."""
    if isinstance(dataloader, DataLoaderDispatcher):
        dataloader.inner.skip_batches = num_batches
        return dataloader
    if isinstance(dataloader, BaseDataLoader):
        dataloader.skip_batches = num_batches
        return dataloader
    raise TypeError("skip_first_batches expects a loader returned by prepare()/prepare_data_loader()")
