"""Autoregressive generation with a jitted KV-cache decode loop.

The reference has no in-framework generation — its big-model-inference
benchmark (benchmarks/big_model_inference, per-token latency table in
BASELINE.md) calls ``transformers`` ``generate`` over dispatched modules.
Here decode is first-class and TPU-shaped:

* the KV cache is a fixed-size pytree (``models/llama.py``
  ``_cached_attention``) updated via ``dynamic_update_slice`` — static
  shapes end to end;
* prefill is ONE forward over the whole prompt (MXU-friendly), then the
  per-token loop is a single ``lax.scan`` inside one jit: no per-token
  dispatch, no host round-trips until the final token block returns;
* sampling (greedy / temperature / top-k) happens on-device inside the
  scan with an explicit folded key chain.

Works with any model whose ``apply_fn`` supports
``(params, ids, positions=..., decode=True, cache=...) -> (logits, cache)``
(the zoo's llama; the same contract is the extension point for others).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _jax():
    import jax

    return jax


def _params_mesh(params):
    """The mesh the model's params live on, if they are mesh-sharded.

    This is what makes ``generate`` multi-device (the reference's headline
    big-model story: inference.py:124-184 prepare_pippy, big_modeling.py:309
    dispatch_model): a model prepared with TP/FSDP rules — or sharded by
    hand — decodes in place, params never leave their shards, and the KV
    cache is laid out on the same mesh (ops/kv_cache.CACHE_KV_SPEC).
    """
    jax = _jax()
    for leaf in jax.tree_util.tree_leaves(params):
        s = getattr(leaf, "sharding", None)
        if isinstance(s, jax.sharding.NamedSharding) and s.mesh.size > 1:
            return s.mesh
    return None


def _shard_batch(x, mesh):
    """Lay a [B, ...] host batch out over the mesh's data-parallel axes
    (replicated if B doesn't divide them, or on meshes without those axes)."""
    jax = _jax()
    from .parallel.mesh import BATCH_AXES
    from .parallel.sharding import _prune_spec
    from jax.sharding import NamedSharding, PartitionSpec

    spec = _prune_spec(
        PartitionSpec(BATCH_AXES), getattr(x, "ndim", 1), getattr(x, "shape", (1,)), mesh, lenient=True
    )
    return jax.device_put(x, NamedSharding(mesh, spec))


def _trace_ctx(mesh):
    """Context under which the decode program is traced: pins ``mesh`` for
    the cache/activation sharding constraints inside model code."""
    import contextlib

    if mesh is None:
        return contextlib.nullcontext()
    from .parallel.sharding import mesh_context

    return mesh_context(mesh)


def _make_sampler(temperature: float, top_k: Optional[int]):
    """Greedy / temperature / top-k token sampler shared by the decoder-only
    and encoder-decoder loops."""
    jax = _jax()
    jnp = jax.numpy

    def sample(logits_1, key):
        logits_1 = logits_1.astype(jnp.float32)
        if temperature <= 0.0:
            return jnp.argmax(logits_1, axis=-1).astype(jnp.int32)
        if top_k is not None:
            kth = jax.lax.top_k(logits_1, top_k)[0][..., -1:]
            logits_1 = jnp.where(logits_1 < kth, -jnp.inf, logits_1)
        return jax.random.categorical(key, logits_1 / temperature, axis=-1).astype(jnp.int32)

    return sample


def _freeze_after_eos(nxt, done, eos_token_id):
    """EOS semantics shared by both loops: finished rows keep emitting EOS."""
    jnp = _jax().numpy
    if eos_token_id is None:
        return nxt, done
    nxt = jnp.where(done, eos_token_id, nxt)
    return nxt, done | (nxt == eos_token_id)


def _scan_new_tokens(step, carry, next_tok, max_new_tokens: int):
    """Run the per-token scan and assemble [B, max_new_tokens] including the
    already-sampled first token."""
    jax = _jax()
    jnp = jax.numpy
    if max_new_tokens > 1:
        _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
        return jnp.concatenate([next_tok[None], rest], axis=0).T
    return next_tok[:, None]


def generate(
    model,
    input_ids,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    seed: int = 0,
    eos_token_id: Optional[int] = None,
):
    """Generate ``max_new_tokens`` continuations of ``input_ids`` [B, S].

    ``temperature=0`` is greedy; otherwise softmax sampling at the given
    temperature, optionally truncated to the ``top_k`` highest logits.
    Returns int32 [B, S + max_new_tokens]. When ``eos_token_id`` is given,
    positions after a sequence's EOS are filled with EOS (the loop still
    runs to ``max_new_tokens`` — static shapes; early exit would retrace).
    """
    jax = _jax()
    jnp = jax.numpy

    apply_fn = model.apply_fn
    params = model.params
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, prompt_len = input_ids.shape

    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return input_ids

    max_pos = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    if max_pos is not None and prompt_len + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model's cache size (max_position_embeddings={max_pos}); "
            f"dynamic_update_slice would silently wrap and corrupt the output"
        )

    # mesh-sharded decode: if the params live on a multi-device mesh, the
    # batch is laid out over its data axes and the decode program is traced
    # with that mesh pinned (KV cache sharded over tensor/data inside)
    mesh = _params_mesh(params)
    if mesh is not None:
        input_ids = _shard_batch(input_ids, mesh)

    # the jitted runner is cached on the model: a fresh jit closure per
    # call would retrace + recompile every generate() (and defeat
    # per_token_latency's warm-up)
    mesh_key = None if mesh is None else tuple(sorted(mesh.shape.items()))
    cache_key = (b, prompt_len, max_new_tokens, float(temperature), top_k, eos_token_id, mesh_key)
    runners = model.__dict__.setdefault("_generate_runners", {})
    if cache_key in runners:
        # still under the mesh context: jit may retrace on new avals (e.g.
        # params re-cast), and a retrace without the mesh pinned would drop
        # the KV-cache sharding constraints
        with _trace_ctx(mesh):
            return runners[cache_key](params, input_ids, jax.random.key(seed))

    @jax.jit
    def run(params, input_ids, key):
        # prefill: one big forward primes the cache and yields the first
        # next-token logits
        positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
        logits, cache = apply_fn(params, input_ids, positions=positions, decode=True, cache=None)

        sample = _make_sampler(temperature, top_k)
        key, sub = jax.random.split(key)
        next_tok = sample(logits[:, -1], sub)
        done = jnp.zeros((b,), bool) if eos_token_id is None else next_tok == eos_token_id

        def step(carry, _):
            cache, tok, pos, key, done = carry
            positions = jnp.broadcast_to(pos[None, None], (b, 1))
            logits, cache = apply_fn(params, tok[:, None], positions=positions, decode=True, cache=cache)
            key, sub = jax.random.split(key)
            nxt, done = _freeze_after_eos(sample(logits[:, -1], sub), done, eos_token_id)
            return (cache, nxt, pos + 1, key, done), nxt

        carry = (cache, next_tok, jnp.int32(prompt_len), key, done)
        new_tokens = _scan_new_tokens(step, carry, next_tok, max_new_tokens)
        return jnp.concatenate([input_ids, new_tokens], axis=1)

    with _trace_ctx(mesh):
        out = run(params, input_ids, jax.random.key(seed))
    runners[cache_key] = run  # register only after a successful first trace
    return out


def generate_seq2seq(
    model,
    input_ids,
    max_new_tokens: int = 32,
    decoder_start_token_id: int = 0,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    seed: int = 0,
    eos_token_id: Optional[int] = None,
    attention_mask=None,
):
    """Encoder-decoder generation (T5 contract): encode once, then a jitted
    ``lax.scan`` decode loop against the decoder KV cache — the encoder
    output persists in the cache, so per-token steps never touch it.

    ``apply_fn(params, input_ids, decoder_input_ids, attention_mask=...,
    decode=True, cache=...) -> (logits, cache)``. Returns int32
    ``[B, 1 + max_new_tokens]`` starting with ``decoder_start_token_id``.
    """
    jax = _jax()
    jnp = jax.numpy

    apply_fn = model.apply_fn
    params = model.params
    # token ids for text encoders; float features (e.g. log-mels) pass as-is
    input_ids = jnp.asarray(input_ids)
    if jnp.issubdtype(input_ids.dtype, jnp.integer):
        input_ids = input_ids.astype(jnp.int32)
    b, src_len = input_ids.shape[:2]
    if attention_mask is None:
        attention_mask = jnp.ones((b, src_len), bool)

    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    start = jnp.full((b, 1), decoder_start_token_id, jnp.int32)
    if max_new_tokens == 0:
        return start

    # exactly max_new_tokens cache slots are written (start token at 0, then
    # the scan's max_new_tokens - 1 steps; the final sample is never cached)
    max_dec = getattr(getattr(model, "config", None), "max_decode_len", None)
    if max_dec is not None and max_new_tokens > max_dec:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) exceeds the decoder cache "
            f"(max_decode_len={max_dec})"
        )

    mesh = _params_mesh(params)
    if mesh is not None:
        input_ids = _shard_batch(input_ids, mesh)
        attention_mask = _shard_batch(attention_mask, mesh)

    mesh_key = None if mesh is None else tuple(sorted(mesh.shape.items()))
    cache_key = ("s2s", b, src_len, max_new_tokens, decoder_start_token_id,
                 float(temperature), top_k, eos_token_id, mesh_key)
    runners = model.__dict__.setdefault("_generate_runners", {})
    if cache_key in runners:
        with _trace_ctx(mesh):
            return runners[cache_key](params, input_ids, attention_mask, jax.random.key(seed))

    @jax.jit
    def run(params, input_ids, attention_mask, key):
        # prefill: encoder + first decoder step on the start token
        logits, cache = apply_fn(
            params, input_ids, start, attention_mask=attention_mask, decode=True, cache=None
        )

        sample = _make_sampler(temperature, top_k)
        key, sub = jax.random.split(key)
        next_tok = sample(logits[:, -1], sub)
        done = jnp.zeros((b,), bool) if eos_token_id is None else next_tok == eos_token_id

        def step(carry, _):
            cache, tok, key, done = carry
            logits, cache = apply_fn(params, input_ids, tok[:, None], decode=True, cache=cache)
            key, sub = jax.random.split(key)
            nxt, done = _freeze_after_eos(sample(logits[:, -1], sub), done, eos_token_id)
            return (cache, nxt, key, done), nxt

        carry = (cache, next_tok, key, done)
        new_tokens = _scan_new_tokens(step, carry, next_tok, max_new_tokens)
        return jnp.concatenate([start, new_tokens], axis=1)

    with _trace_ctx(mesh):
        out = run(params, input_ids, attention_mask, jax.random.key(seed))
    runners[cache_key] = run  # register only after a successful first trace
    return out


def beam_search(
    model,
    input_ids,
    max_new_tokens: int = 32,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id: Optional[int] = None,
    return_scores: bool = False,
):
    """Beam-search decode of ``input_ids`` [B, S] (the remaining decode
    mode of the transformers ``generate`` surface; reference delegates it).

    One jitted program: prefill → expand the KV cache to ``num_beams``
    rows per batch element → ``lax.scan`` steps that (a) score every
    (beam, token) continuation, (b) keep the top ``num_beams`` per batch,
    and (c) REORDER the cache rows along the chosen beams. EOS beams are
    frozen (score fixed, forced EOS continuation); the returned sequence
    per batch element maximises ``score / len(new_tokens)**length_penalty``.
    Returns int32 [B, S + max_new_tokens] (plus [B] normalised scores when
    ``return_scores``).
    """
    jax = _jax()
    jnp = jax.numpy

    apply_fn = model.apply_fn
    params = model.params
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, prompt_len = input_ids.shape
    k = num_beams
    if k < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    max_pos = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    if max_pos is not None and prompt_len + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model's cache size (max_position_embeddings={max_pos})"
        )

    mesh = _params_mesh(params)
    if mesh is not None:
        input_ids = _shard_batch(input_ids, mesh)

    cache_key = ("beam", b, prompt_len, max_new_tokens, k, float(length_penalty),
                 eos_token_id, None if mesh is None else tuple(sorted(mesh.shape.items())))
    runners = model.__dict__.setdefault("_generate_runners", {})
    if cache_key in runners:
        with _trace_ctx(mesh):
            out = runners[cache_key](params, input_ids)
            return out if return_scores else out[0]

    NEG = jnp.float32(-1e9)

    @jax.jit
    def run(params, input_ids):
        positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
        logits, cache = apply_fn(params, input_ids, positions=positions, decode=True, cache=None)
        logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)  # [B, V]
        vocab = logp0.shape[-1]

        # distinct first tokens seed the beams. Cache k/v buffers are
        # [..., B, max_len, H, D] (a leading layer dim when scanned), so the
        # batch axis is ndim-4; scalar index leaves have no batch dim.
        scores, tok0 = jax.lax.top_k(logp0, k)  # [B, K]

        def batch_repeat(l):
            return jnp.repeat(l, k, axis=l.ndim - 4) if l.ndim >= 4 else l

        def batch_gather(l, rows):
            return jnp.take(l, rows, axis=l.ndim - 4) if l.ndim >= 4 else l

        cache = jax.tree.map(batch_repeat, cache)  # [.., B*K, ...]

        done = (tok0 == eos_token_id) if eos_token_id is not None else jnp.zeros((b, k), bool)
        lengths = jnp.ones((b, k), jnp.int32)
        tokens = jnp.zeros((b, k, max_new_tokens), jnp.int32).at[:, :, 0].set(tok0)

        def step(carry, t):
            cache, last, scores, done, lengths, tokens = carry
            # ``last`` was emitted at scan step t-1 and occupies sequence
            # position prompt_len + t - 1
            positions = jnp.broadcast_to(prompt_len + t - 1, (b * k, 1))
            logits, cache = apply_fn(
                params, last.reshape(b * k, 1), positions=positions, decode=True, cache=cache
            )
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1).reshape(b, k, vocab)
            # live beams extend by any token; done beams may only "extend"
            # by EOS at unchanged score (frozen)
            cand = scores[:, :, None] + logp
            if eos_token_id is not None:
                frozen = jnp.full((b, k, vocab), NEG).at[:, :, eos_token_id].set(0.0) + scores[:, :, None]
                cand = jnp.where(done[:, :, None], frozen, cand)
            flat = cand.reshape(b, k * vocab)
            scores, idx = jax.lax.top_k(flat, k)  # [B, K]
            beam_idx = idx // vocab  # [B, K] source beam
            tok = (idx % vocab).astype(jnp.int32)

            batch_arange = jnp.arange(b)[:, None]
            rows = (batch_arange * k + beam_idx).reshape(-1)  # [B*K] cache row gather
            cache = jax.tree.map(lambda l: batch_gather(l, rows), cache)
            done = jnp.take_along_axis(done, beam_idx, axis=1)
            lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
            tokens = jnp.take_along_axis(tokens, beam_idx[:, :, None], axis=1)

            lengths = lengths + (~done).astype(jnp.int32)
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
            tokens = tokens.at[:, :, t].set(tok)
            return (cache, tok, scores, done, lengths, tokens), None

        if max_new_tokens > 1:
            carry = (cache, tok0, scores, done, lengths, tokens)
            (cache, _, scores, done, lengths, tokens), _ = jax.lax.scan(
                step, carry, jnp.arange(1, max_new_tokens)
            )

        norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
        best = jnp.argmax(norm, axis=1)  # [B]
        best_tokens = jnp.take_along_axis(tokens, best[:, None, None], axis=1)[:, 0]  # [B, T]
        out = jnp.concatenate([input_ids, best_tokens], axis=1)
        return out, jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]

    with _trace_ctx(mesh):
        out = run(params, input_ids)
    runners[cache_key] = run
    return out if return_scores else out[0]


def per_token_latency(model, batch_size: int = 1, prompt_len: int = 32, n_tokens: int = 16) -> float:
    """Measure steady-state per-token decode latency in seconds (the
    reference's big-model-inference metric, benchmarks README "per-token").

    Method: time one LONG decode (``16 * n_tokens`` steps) and one short
    one (``n_tokens``), difference, and divide by the step delta. Both
    runs carry identical prefill + dispatch overhead, so the difference
    isolates pure decode steps; the long run is long enough (>= 128 steps
    by default) that host/tunnel jitter — tens of ms on remote-attached
    backends — stays small relative to the measured span. (An earlier
    short-pair variant of this measurement was dominated by that jitter
    and over-reported quantized decode by ~7x.)
    """
    import time

    ids = np.ones((batch_size, prompt_len), np.int32)
    n_long, n_short = 16 * n_tokens, n_tokens
    # clamp to the model's KV-cache budget (generate() rejects overruns)
    max_pos = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    if max_pos is not None and prompt_len + n_long > max_pos:
        n_long = max_pos - prompt_len
        n_short = max(1, n_long // 16)
        if n_long <= n_short:
            raise ValueError(
                f"cache too small to measure: prompt {prompt_len} leaves {n_long} decode steps "
                f"(max_position_embeddings={max_pos})"
            )

    def sync(out):
        # value fetch, not block_until_ready: remote-attached backends (the
        # axon tunnel) return from block_until_ready before execution
        # finishes, which would time dispatch instead of decode. The last
        # token depends on the full decode chain, so fetching it is a true
        # barrier.
        int(out[0, -1])

    def timed(n):
        t0 = time.perf_counter()
        out = generate(model, ids, max_new_tokens=n)
        sync(out)
        return time.perf_counter() - t0

    # compile/warm each token count once; the jitted runner is cached on
    # the model, so the timed runs below measure pure execution
    for n in (n_long, n_short):
        sync(generate(model, ids, max_new_tokens=n))

    best = min(timed(n_long) - timed(n_short) for _ in range(2))
    if best <= 0:
        # noise swamped the signal — report the amortized whole-run cost
        # (a conservative upper bound incl. prefill)
        return timed(n_long) / n_long
    return best / (n_long - n_short)
