"""UNet2D diffusion denoiser (flax.linen) + sinusoidal time conditioning.

The diffusion family of the zoo — the reference's distributed-inference
examples generate images with diffusers pipelines
(reference: examples/inference/distributed/stable_diffusion.py,
distributed_image_generation.py); here the denoiser itself is in-tree and
TPU-shaped:

* NHWC layout end-to-end (TPU conv layout; torch diffusers is NCHW);
* GroupNorm statistics in fp32 under the bf16 policy (same stance as
  RMSNorm in the llama family);
* the sampling loop lives in :mod:`.diffusion` as one ``lax.scan`` —
  static shapes, one compile, no per-step dispatch (the decode-loop
  design of generation.py, applied to denoising steps);
* optional class conditioning via a label embedding added to the time
  embedding (classifier-free guidance ready: pass ``num_classes`` and
  reserve the last id as the null token).

Sharding rules split conv output channels / attention heads over
``tensor`` — the Megatron column/row pattern applied to convs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 3
    out_channels: int = 3
    sample_size: int = 32  # H = W
    base_channels: int = 64
    channel_mults: Sequence[int] = (1, 2, 2)
    layers_per_block: int = 1
    attention_levels: Sequence[int] = (2,)  # indices into channel_mults
    num_heads: int = 4
    num_groups: int = 8
    num_classes: Optional[int] = None  # class-conditional when set
    context_dim: Optional[int] = None  # cross-attention text conditioning when set
    dropout: float = 0.0

    @classmethod
    def tiny(cls, **kw) -> "UNetConfig":
        kw.setdefault("sample_size", 8)
        kw.setdefault("base_channels", 16)
        kw.setdefault("channel_mults", (1, 2))
        kw.setdefault("attention_levels", (1,))
        kw.setdefault("num_groups", 4)
        kw.setdefault("num_heads", 2)
        return cls(**kw)


UNET_SHARDING_RULES = [
    # conv kernels [kh, kw, in, out]: the Megatron column/row pair per
    # ResBlock — conv_1 column-splits the out channels, conv_2 row-splits
    # the in channels (GSPMD inserts the psum), so every block's OUTPUT is
    # replicated over `tensor`. Skip tensors must never be channel-sharded:
    # XLA's SPMD partitioner miscompiles `concatenate` along a dimension
    # sharded over one axis of a multi-axis mesh (observed on XLA:CPU,
    # jax 0.4.37 — wrong values, not reduction-order noise), and the up
    # path concatenates every skip along channels.
    (r"conv_1/kernel", P(None, None, None, "tensor")),
    (r"conv_2/kernel", P(None, None, "tensor", None)),
    (r"conv_out/kernel", P(None, None, "tensor", None)),
    # attention projections (self and cross): column qkv, row out
    (r"(cross_)?(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"(cross_)?out_proj/kernel", P("tensor", None)),
    # time embedding MLP: column then row, temb stays replicated
    (r"time_mlp_1/kernel", P(None, "tensor")),
    (r"time_mlp_2/kernel", P("tensor", None)),
]


def _skip_safe(h):
    """Constrain an activation headed for a skip concat to the
    batch-sharded/channel-replicated layout. Without the annotation GSPMD
    may propagate a column-split conv's channel sharding into the skip list
    and partition the up-path ``concatenate`` along channels — the layout
    the row-split convs make redundant anyway, and the one XLA's SPMD
    partitioner gets wrong on multi-axis meshes (see UNET_SHARDING_RULES).
    No-op when no mesh is active."""
    from ..parallel.mesh import BATCH_AXES
    from ..parallel.sharding import maybe_shard

    return maybe_shard(h, P(BATCH_AXES))


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal embedding [B] -> [B, dim] (DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class _GroupNorm(nn.Module):
    groups: int

    @nn.compact
    def __call__(self, x):
        # statistics in fp32, output back in the stream dtype
        return nn.GroupNorm(num_groups=self.groups, dtype=jnp.float32, name="gn")(
            x.astype(jnp.float32)
        ).astype(x.dtype)


class ResBlock(nn.Module):
    channels: int
    groups: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, temb, deterministic: bool = True):
        h = nn.silu(_GroupNorm(self.groups, name="norm_1")(x))
        h = nn.Conv(self.channels, (3, 3), padding="SAME", name="conv_1", dtype=x.dtype)(h)
        # FiLM-style scale/shift from the time embedding
        ss = nn.Dense(2 * self.channels, name="temb_proj", dtype=x.dtype)(nn.silu(temb))
        scale, shift = jnp.split(ss[:, None, None, :], 2, axis=-1)
        h = _GroupNorm(self.groups, name="norm_2")(h) * (1 + scale) + shift
        h = nn.silu(h)
        if self.dropout > 0.0:
            h = nn.Dropout(self.dropout, deterministic=deterministic)(h)
        h = nn.Conv(self.channels, (3, 3), padding="SAME", name="conv_2", dtype=x.dtype)(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), name="skip_proj", dtype=x.dtype)(x)
        return x + h


class AttnBlock(nn.Module):
    """Self-attention over spatial positions; with ``context`` also a
    cross-attention sub-block whose keys/values come from the conditioning
    sequence (the latent-diffusion transformer block — reference pipelines
    get this from diffusers' ``Transformer2DModel``)."""

    num_heads: int
    groups: int

    @nn.compact
    def __call__(self, x, context=None):
        b, hh, ww, c = x.shape
        head_dim = c // self.num_heads
        from ..ops.attention import dot_product_attention

        def split(y):
            return y.reshape(b, -1, self.num_heads, head_dim)

        h = _GroupNorm(self.groups, name="norm")(x).reshape(b, hh * ww, c)
        q = split(nn.Dense(c, name="q_proj", dtype=x.dtype)(h))
        k = split(nn.Dense(c, name="k_proj", dtype=x.dtype)(h))
        v = split(nn.Dense(c, name="v_proj", dtype=x.dtype)(h))
        out = dot_product_attention(q, k, v, causal=False).reshape(b, hh * ww, c)
        x = x + nn.Dense(c, name="out_proj", dtype=x.dtype)(out).reshape(b, hh, ww, c)

        if context is not None:
            ctx = context.astype(x.dtype)
            h = _GroupNorm(self.groups, name="cross_norm")(x).reshape(b, hh * ww, c)
            q = split(nn.Dense(c, name="cross_q_proj", dtype=x.dtype)(h))
            k = split(nn.Dense(c, name="cross_k_proj", dtype=x.dtype)(ctx))
            v = split(nn.Dense(c, name="cross_v_proj", dtype=x.dtype)(ctx))
            out = dot_product_attention(q, k, v, causal=False).reshape(b, hh * ww, c)
            x = x + nn.Dense(c, name="cross_out_proj", dtype=x.dtype)(out).reshape(b, hh, ww, c)
        return x


class UNet2D(nn.Module):
    config: UNetConfig

    @nn.compact
    def __call__(self, sample, timesteps, class_labels=None, encoder_hidden_states=None, deterministic: bool = True):
        """``sample`` [B, H, W, C] (NHWC), ``timesteps`` [B] int/float,
        optional ``class_labels`` [B], optional ``encoder_hidden_states``
        [B, T, context_dim] (per-token text states for cross-attention —
        requires ``config.context_dim``). Returns the predicted noise
        [B, H, W, out_channels]."""
        cfg = self.config
        if cfg.context_dim is not None and encoder_hidden_states is None:
            raise ValueError("text-conditional UNet needs encoder_hidden_states")
        ctx = encoder_hidden_states if cfg.context_dim is not None else None
        temb_dim = cfg.base_channels * 4
        temb = timestep_embedding(timesteps, cfg.base_channels).astype(sample.dtype)
        temb = nn.Dense(temb_dim, name="time_mlp_1", dtype=sample.dtype)(temb)
        temb = nn.Dense(temb_dim, name="time_mlp_2", dtype=sample.dtype)(nn.silu(temb))
        if cfg.num_classes is not None:
            if class_labels is None:
                raise ValueError("class-conditional UNet needs class_labels")
            temb = temb + nn.Embed(cfg.num_classes, temb_dim, name="label_embed")(class_labels).astype(temb.dtype)

        h = nn.Conv(cfg.base_channels, (3, 3), padding="SAME", name="conv_in", dtype=sample.dtype)(sample)
        skips = [_skip_safe(h)]
        # down path
        for lvl, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            for i in range(cfg.layers_per_block):
                h = ResBlock(ch, cfg.num_groups, cfg.dropout, name=f"down_{lvl}_{i}")(h, temb, deterministic)
                if lvl in cfg.attention_levels:
                    h = AttnBlock(cfg.num_heads, cfg.num_groups, name=f"down_attn_{lvl}_{i}")(h, ctx)
                skips.append(_skip_safe(h))
            if lvl != len(cfg.channel_mults) - 1:
                h = nn.Conv(ch, (3, 3), (2, 2), padding="SAME", name=f"downsample_{lvl}", dtype=h.dtype)(h)
                skips.append(_skip_safe(h))
        # mid
        ch = cfg.base_channels * cfg.channel_mults[-1]
        h = ResBlock(ch, cfg.num_groups, cfg.dropout, name="mid_1")(h, temb, deterministic)
        h = AttnBlock(cfg.num_heads, cfg.num_groups, name="mid_attn")(h, ctx)
        h = ResBlock(ch, cfg.num_groups, cfg.dropout, name="mid_2")(h, temb, deterministic)
        # up path (skip concats, mirror order)
        for lvl, mult in reversed(list(enumerate(cfg.channel_mults))):
            ch = cfg.base_channels * mult
            for i in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([_skip_safe(h), skips.pop()], axis=-1)
                h = ResBlock(ch, cfg.num_groups, cfg.dropout, name=f"up_{lvl}_{i}")(h, temb, deterministic)
                if lvl in cfg.attention_levels:
                    h = AttnBlock(cfg.num_heads, cfg.num_groups, name=f"up_attn_{lvl}_{i}")(h, ctx)
            if lvl != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = nn.Conv(ch, (3, 3), padding="SAME", name=f"upsample_{lvl}", dtype=h.dtype)(h)
        h = nn.silu(_GroupNorm(cfg.num_groups, name="norm_out")(h))
        return nn.Conv(cfg.out_channels, (3, 3), padding="SAME", name="conv_out", dtype=jnp.float32)(h)


def create_unet_model(config: Optional[UNetConfig] = None, seed: int = 0, batch_size: int = 2) -> Model:
    config = config or UNetConfig.tiny()
    module = UNet2D(config)
    sample = jnp.zeros((batch_size, config.sample_size, config.sample_size, config.in_channels), jnp.float32)
    t = jnp.zeros((batch_size,), jnp.int32)
    kwargs = {}
    if config.num_classes:
        kwargs["class_labels"] = jnp.zeros((batch_size,), jnp.int32)
    if config.context_dim:
        kwargs["encoder_hidden_states"] = jnp.zeros((batch_size, 4, config.context_dim), jnp.float32)
    params = module.init(jax.random.key(seed), sample, t, **kwargs)["params"]

    def apply_fn(p, sample, timesteps, class_labels=None, encoder_hidden_states=None, deterministic=True):
        leaf = jax.tree_util.tree_leaves(p)[0]
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            sample = sample.astype(leaf.dtype)
        kwargs = {"deterministic": deterministic}
        if class_labels is not None:
            kwargs["class_labels"] = class_labels
        if encoder_hidden_states is not None:
            kwargs["encoder_hidden_states"] = encoder_hidden_states
        return module.apply({"params": p}, sample, timesteps, **kwargs)

    model = Model(apply_fn, params, sharding_rules=UNET_SHARDING_RULES, name="unet2d")
    model.config = config
    model.module = module
    return model
