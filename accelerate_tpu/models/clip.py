"""CLIP dual encoder (flax.linen): ViT vision tower + causal text tower,
projection heads, learned logit scale, contrastive loss.

Multi-modal family of the zoo (structure matches HF ``CLIPModel`` for
element-wise checkpoint import). The TPU-interesting part is the
contrastive loss: torch implementations must all-gather embeddings across
data-parallel ranks by hand (open_clip's ``gather_with_grad``) to score
global-batch negatives; under GSPMD the loss is written over the global
batch and XLA inserts the gathers — ``clip_contrastive_loss`` is the
plain similarity matrix, sharded in, replicated math out.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class CLIPConfig:
    # vision tower
    image_size: int = 224
    patch_size: int = 32
    vision_hidden_size: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    vision_ffn_dim: int = 3072
    # text tower
    vocab_size: int = 49408
    max_text_positions: int = 77
    text_hidden_size: int = 512
    text_layers: int = 12
    text_heads: int = 8
    text_ffn_dim: int = 2048
    eos_token_id: int = 49407
    # joint space
    projection_dim: int = 512
    logit_scale_init: float = 2.6592  # ln(1/0.07), HF default
    layer_norm_eps: float = 1e-5

    @classmethod
    def tiny(cls, **kw) -> "CLIPConfig":
        kw.setdefault("image_size", 16)
        kw.setdefault("patch_size", 8)
        kw.setdefault("vision_hidden_size", 32)
        kw.setdefault("vision_layers", 2)
        kw.setdefault("vision_heads", 4)
        kw.setdefault("vision_ffn_dim", 64)
        kw.setdefault("vocab_size", 128)
        kw.setdefault("max_text_positions", 16)
        kw.setdefault("text_hidden_size", 32)
        kw.setdefault("text_layers", 2)
        kw.setdefault("text_heads", 4)
        kw.setdefault("text_ffn_dim", 64)
        kw.setdefault("eos_token_id", 2)
        kw.setdefault("projection_dim", 32)
        return cls(**kw)


CLIP_SHARDING_RULES = [
    (r"(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"out_proj/kernel", P("tensor", None)),
    (r"fc1/kernel", P(None, "tensor")),
    (r"fc2/kernel", P("tensor", None)),
    (r"token_embed/embedding", P("tensor", None)),
    (r"(visual|text)_projection/kernel", P(None, "tensor")),
]


def quick_gelu(x):
    """CLIP's activation: x * sigmoid(1.702 x) (HF ``quick_gelu``)."""
    return x * jax.nn.sigmoid(1.702 * x)


class CLIPBlock(nn.Module):
    d_model: int
    num_heads: int
    ffn_dim: int
    eps: float
    causal: bool = False

    @nn.compact
    def __call__(self, hidden):
        head_dim = self.d_model // self.num_heads

        def split(x):
            return x.reshape(*x.shape[:-1], self.num_heads, head_dim)

        h = nn.LayerNorm(epsilon=self.eps, name="ln1", dtype=hidden.dtype)(hidden)
        q = split(nn.Dense(self.d_model, name="q_proj", dtype=h.dtype)(h))
        k = split(nn.Dense(self.d_model, name="k_proj", dtype=h.dtype)(h))
        v = split(nn.Dense(self.d_model, name="v_proj", dtype=h.dtype)(h))
        from ..ops.attention import dot_product_attention

        out = dot_product_attention(q, k, v, causal=self.causal)
        out = out.reshape(*out.shape[:-2], self.d_model)
        hidden = hidden + nn.Dense(self.d_model, name="out_proj", dtype=h.dtype)(out)

        h = nn.LayerNorm(epsilon=self.eps, name="ln2", dtype=hidden.dtype)(hidden)
        h = quick_gelu(nn.Dense(self.ffn_dim, name="fc1", dtype=h.dtype)(h))
        return hidden + nn.Dense(self.d_model, name="fc2", dtype=h.dtype)(h)


class CLIPModel(nn.Module):
    config: CLIPConfig

    @nn.compact
    def __call__(self, pixel_values=None, input_ids=None, output_hidden: bool = False):
        """Returns ``(image_embeds, text_embeds, logit_scale)`` — embeds are
        L2-normalised rows in the joint space; either input may be None to
        run one tower. ``pixel_values`` [B, H, W, 3] NHWC. With
        ``output_hidden=True`` a 4th element is appended: the text tower's
        final-norm per-token states [B, T, D] (what latent-diffusion
        cross-attention conditions on — HF `CLIPTextModel.last_hidden_state`)."""
        cfg = self.config
        image_embeds = text_embeds = text_hidden = None

        if pixel_values is not None:
            p = cfg.patch_size
            x = nn.Conv(
                cfg.vision_hidden_size, (p, p), strides=(p, p), padding="VALID",
                use_bias=False, name="vision/patch_embed", dtype=pixel_values.dtype,
            )(pixel_values)
            b, gh, gw, c = x.shape
            x = x.reshape(b, gh * gw, c)
            cls = self.param("vision/class_embedding", nn.initializers.normal(0.02), (c,))
            x = jnp.concatenate([jnp.broadcast_to(cls.astype(x.dtype), (b, 1, c)), x], axis=1)
            pos = self.param(
                "vision/pos_embed/embedding", nn.initializers.normal(0.02), (gh * gw + 1, c)
            )
            x = x + pos[None].astype(x.dtype)
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="vision/pre_norm", dtype=x.dtype)(x)
            for i in range(cfg.vision_layers):
                x = CLIPBlock(
                    cfg.vision_hidden_size, cfg.vision_heads, cfg.vision_ffn_dim,
                    cfg.layer_norm_eps, name=f"vision/block_{i}",
                )(x)
            pooled = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="vision/post_norm", dtype=x.dtype)(x)[:, 0]
            image_embeds = nn.Dense(
                cfg.projection_dim, use_bias=False, name="visual_projection", dtype=pooled.dtype
            )(pooled)
            image_embeds = image_embeds / jnp.linalg.norm(image_embeds, axis=-1, keepdims=True)

        if input_ids is not None:
            tok = nn.Embed(cfg.vocab_size, cfg.text_hidden_size, name="text/token_embed")
            t = tok(input_ids)
            tpos = self.param(
                "text/pos_embed/embedding", nn.initializers.normal(0.02),
                (cfg.max_text_positions, cfg.text_hidden_size),
            )
            t = t + tpos[None, : t.shape[1]].astype(t.dtype)
            for i in range(cfg.text_layers):
                t = CLIPBlock(
                    cfg.text_hidden_size, cfg.text_heads, cfg.text_ffn_dim,
                    cfg.layer_norm_eps, causal=True, name=f"text/block_{i}",
                )(t)
            t = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="text/final_norm", dtype=t.dtype)(t)
            text_hidden = t
            # pooled = hidden state at the EOS token, HF semantics
            # (modeling_clip.py CLIPTextTransformer.forward): legacy configs
            # with eos_token_id==2 pool at argmax(input_ids) — OpenAI CLIP's
            # real EOT (49407) is the highest vocab id, so argmax finds it
            # even though the config says 2. Newer configs pool at the first
            # occurrence of eos_token_id.
            if cfg.eos_token_id == 2:
                eos_pos = jnp.argmax(input_ids, axis=-1)
            else:
                eos_pos = jnp.argmax((input_ids == cfg.eos_token_id).astype(jnp.int32), axis=-1)
            pooled = t[jnp.arange(t.shape[0]), eos_pos]
            text_embeds = nn.Dense(
                cfg.projection_dim, use_bias=False, name="text_projection", dtype=pooled.dtype
            )(pooled)
            text_embeds = text_embeds / jnp.linalg.norm(text_embeds, axis=-1, keepdims=True)

        logit_scale = self.param(
            "logit_scale", lambda key: jnp.asarray(cfg.logit_scale_init, jnp.float32)
        )
        if output_hidden:
            return image_embeds, text_embeds, logit_scale, text_hidden
        return image_embeds, text_embeds, logit_scale


def create_clip_model(config: Optional[CLIPConfig] = None, seed: int = 0, batch_size: int = 2) -> Model:
    config = config or CLIPConfig.tiny()
    module = CLIPModel(config)
    pix = jnp.zeros((batch_size, config.image_size, config.image_size, 3), jnp.float32)
    ids = jnp.zeros((batch_size, config.max_text_positions), jnp.int32)
    params = module.init(jax.random.key(seed), pix, ids)["params"]

    def apply_fn(p, pixel_values=None, input_ids=None):
        leaf = jax.tree_util.tree_leaves(p)[0]
        if pixel_values is not None and jnp.issubdtype(leaf.dtype, jnp.floating):
            pixel_values = pixel_values.astype(leaf.dtype)
        return module.apply({"params": p}, pixel_values, input_ids)

    model = Model(apply_fn, params, sharding_rules=CLIP_SHARDING_RULES, name="clip")
    model.config = config
    model.module = module

    def encode_text(p, input_ids):
        """Per-token text states [B, T, D] for cross-attention conditioning."""
        return module.apply({"params": p}, None, input_ids, output_hidden=True)[3]

    model.encode_text = encode_text
    return model


def clip_contrastive_loss(params, batch, apply_fn):
    """Symmetric InfoNCE over the GLOBAL batch: similarity of every image
    against every text. Written as plain global-batch math — with the batch
    sharded over ``data``/``fsdp``, GSPMD inserts the all-gathers that
    torch CLIP implementations hand-write (open_clip ``gather_with_grad``),
    and the negatives span all shards automatically."""
    img, txt, logit_scale = apply_fn(params, batch["pixel_values"], batch["input_ids"])
    logits = img.astype(jnp.float32) @ txt.astype(jnp.float32).T * jnp.exp(logit_scale)
    labels = jnp.arange(logits.shape[0])
    logp_i = jax.nn.log_softmax(logits, axis=-1)
    logp_t = jax.nn.log_softmax(logits.T, axis=-1)
    nll_i = -jnp.take_along_axis(logp_i, labels[:, None], axis=-1).mean()
    nll_t = -jnp.take_along_axis(logp_t, labels[:, None], axis=-1).mean()
    return 0.5 * (nll_i + nll_t)
