"""Gemma2: the llama skeleton with Google's second-generation deviations.

On top of Gemma1's knobs (explicit ``head_dim``, GeGLU, ``(1+scale)``
norms, scaled embeddings, tied head), Gemma2 adds the four things that
made it distinctive:

* **sandwich norms** (``sandwich_norm``): pre- AND post-RMSNorm around
  both the attention and MLP sublayers;
* **logit softcapping**: attention scores tanh-bounded at 50
  (``attn_logit_softcap``, applied before the mask) and final logits at
  30 (``final_logit_softcap``);
* **attention scale** from ``query_pre_attn_scalar`` (224 for 9B —
  deliberately NOT head_dim) instead of ``head_dim**-0.5``;
* **alternating local/global attention** (``layer_types``): every other
  layer applies the 4096-token sliding window. Per-layer attention kinds
  need ``scan_layers=False`` (one scanned block shares a static config),
  so Gemma2 defaults to the unrolled stack.

Softcapping runs on the XLA attention path (the flash kernel has no
tanh-cap branch) and the dense KV cache (the paged kernel raises).
Parity vs ``transformers.Gemma2ForCausalLM`` in tests/test_hf_parity.py.
The reference has no in-tree models (SURVEY §2.2); this family is zoo
surface.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

GEMMA2_SHARDING_RULES = LLAMA_SHARDING_RULES
Gemma2Model = LlamaModel


def _alternating(n_layers: int) -> tuple:
    """HF Gemma2 layer pattern: odd layers slide, even layers are global."""
    return tuple(
        "sliding_attention" if bool((i + 1) % 2) else "full_attention" for i in range(n_layers)
    )


@dataclasses.dataclass
class Gemma2Config(LlamaConfig):
    """Llama config with gemma2-9b defaults (sandwich norms, softcaps,
    alternating 4096-token window)."""

    vocab_size: int = 256000
    hidden_size: int = 3584
    intermediate_size: int = 14336
    num_hidden_layers: int = 42
    num_attention_heads: int = 16
    num_key_value_heads: int = 8
    head_dim: Optional[int] = 256
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    mlp_activation: str = "gelu_tanh"
    norm_plus_one: bool = True
    scale_embeddings: bool = True
    tie_word_embeddings: bool = True
    sandwich_norm: bool = True
    attn_logit_softcap: Optional[float] = 50.0
    final_logit_softcap: Optional[float] = 30.0
    query_pre_attn_scalar: Optional[float] = 256.0  # transformers Gemma2Config default
    sliding_window: Optional[int] = 4096
    layer_types: Optional[tuple] = None  # filled per num_hidden_layers below
    scan_layers: bool = False  # per-layer attention kinds need the unrolled stack

    def __post_init__(self):
        if self.layer_types is None:
            self.layer_types = _alternating(self.num_hidden_layers)
        if len(self.layer_types) != self.num_hidden_layers:
            raise ValueError(
                f"layer_types has {len(self.layer_types)} entries for "
                f"{self.num_hidden_layers} layers — pass both together (or neither)"
            )

    @classmethod
    def tiny(cls, **kw) -> "Gemma2Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)  # one sliding + one full layer
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("sliding_window", 8)  # small enough for the band to bite
        kw.setdefault("query_pre_attn_scalar", 32.0)  # != head_dim: scale is load-bearing
        return cls(**kw)

    @classmethod
    def gemma2_9b(cls, **kw) -> "Gemma2Config":
        return cls(**kw)


def create_gemma2_model(config: Optional[Gemma2Config] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with Gemma2's sandwich norms, softcaps, and alternating windows."""
    return create_llama_model(config or Gemma2Config.tiny(), seed=seed, seq_len=seq_len)
