"""ResNet-v1.5 (flax.linen) — the CV-example model.

Reference analogue: examples/cv_example.py trains a timm ResNet-50 on the
Oxford-IIIT Pet dataset; BASELINE.json lists the CV example among the
configs the framework must serve. This is a from-scratch TPU-first
implementation, not a torchvision translation:

* NHWC layout throughout — the TPU convolution layout (XLA:TPU tiles the
  channel dim onto the 128-lane register; NCHW would transpose on every op);
* v1.5 bottleneck (stride on the 3x3, not the 1x1 — the variant every
  modern baseline actually measures);
* BatchNorm running statistics are an explicit non-trainable state pytree
  threaded through ``Accelerator.build_train_step(has_state=True)`` —
  torch mutates BN buffers in place, JAX makes the state visible;
* bf16-friendly: params fp32, compute dtype set by the Accelerator policy.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_filters: int = 64
    num_classes: int = 1000
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    remat: bool = False

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(**kw)

    @classmethod
    def resnet18(cls, **kw) -> "ResNetConfig":
        kw.setdefault("stage_sizes", (2, 2, 2, 2))
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        """Two stages of one block each — CI-sized."""
        kw.setdefault("stage_sizes", (1, 1))
        kw.setdefault("num_filters", 8)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


# The classifier head is the only matmul big enough to split; conv
# out-channels shard over ``tensor`` so an 8-way TP mesh still packs the
# MXU. (The reference delegates all TP to transformers/Megatron and has no
# CV TP story at all — SURVEY §2.2.)
RESNET_SHARDING_RULES = [
    (r"head/kernel", P(None, "tensor")),
    (r"conv_init/kernel", P(None, None, None, "tensor")),
]


class BottleneckBlock(nn.Module):
    """v1.5 bottleneck: 1x1 reduce -> 3x3 (carries the stride) -> 1x1 expand."""

    filters: int
    strides: int
    config: ResNetConfig
    train: bool

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = x.dtype
        conv = lambda f, k, s, name: nn.Conv(f, (k, k), (s, s), padding="SAME", use_bias=False, dtype=dtype, name=name)
        bn = lambda name: nn.BatchNorm(
            use_running_average=not self.train,
            momentum=cfg.bn_momentum,
            epsilon=cfg.bn_epsilon,
            dtype=dtype,
            name=name,
        )

        residual = x
        y = conv(self.filters, 1, 1, "conv1")(x)
        y = nn.relu(bn("bn1")(y))
        y = conv(self.filters, 3, self.strides, "conv2")(y)
        y = nn.relu(bn("bn2")(y))
        y = conv(self.filters * 4, 1, 1, "conv3")(y)
        # zero-init the last BN scale: residual branch starts as identity
        # (the standard trick every strong ResNet baseline uses)
        y = nn.BatchNorm(
            use_running_average=not self.train,
            momentum=cfg.bn_momentum,
            epsilon=cfg.bn_epsilon,
            dtype=dtype,
            scale_init=nn.initializers.zeros_init(),
            name="bn3",
        )(y)

        if residual.shape != y.shape:
            residual = conv(self.filters * 4, 1, self.strides, "conv_proj")(residual)
            residual = bn("bn_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig
    train: bool = False

    @nn.compact
    def __call__(self, images):
        """images: [B, H, W, 3] (NHWC, float). Returns [B, num_classes] fp32."""
        cfg = self.config
        x = images
        x = nn.Conv(cfg.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], use_bias=False, dtype=x.dtype, name="conv_init")(x)
        x = nn.BatchNorm(
            use_running_average=not self.train,
            momentum=cfg.bn_momentum,
            epsilon=cfg.bn_epsilon,
            dtype=x.dtype,
            name="bn_init",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        block_cls = BottleneckBlock
        if cfg.remat:
            block_cls = nn.remat(block_cls, static_argnums=())
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                x = block_cls(
                    filters=cfg.num_filters * 2**i,
                    strides=2 if j == 0 and i > 0 else 1,
                    config=cfg,
                    train=self.train,
                    name=f"stage{i}_block{j}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def create_resnet_model(
    config: Optional[ResNetConfig] = None,
    seed: int = 0,
    image_size: int = 224,
    batch_size: int = 2,
) -> Model:
    """Initialise a :class:`~accelerate_tpu.modeling.Model` wrapping ResNet.

    ``model.state`` holds the BatchNorm running statistics
    (``{"batch_stats": ...}``); train with
    ``build_train_step(resnet_classification_loss, has_state=True)``.
    """
    config = config or ResNetConfig.resnet50()
    dummy = jnp.zeros((batch_size, image_size, image_size, 3), jnp.float32)
    variables = ResNet(config, train=False).init(jax.random.key(seed), dummy)
    params = variables["params"]
    batch_stats = variables["batch_stats"]

    train_module = ResNet(config, train=True)
    eval_module = ResNet(config, train=False)

    def apply_fn(p, images, state=None, train=False, rngs=None):
        """train=True returns (logits, new_state); eval returns logits."""
        # the Accelerator's dtype policy casts PARAMS; convs derive their
        # compute dtype from the activations, so the images must follow
        # the params or fp32 inputs would upcast every layer back to fp32
        leaf = jax.tree_util.tree_leaves(p)[0]
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            images = images.astype(leaf.dtype)
        state = state if state is not None else model.state
        if train:
            logits, updates = train_module.apply(
                {"params": p, **state}, images, mutable=["batch_stats"], rngs=rngs
            )
            return logits, updates
        return eval_module.apply({"params": p, **state}, images)

    model = Model(apply_fn, params, sharding_rules=RESNET_SHARDING_RULES, name="resnet")
    model.state = {"batch_stats": batch_stats}
    model.config = config
    model.module = eval_module
    return model


def resnet_classification_loss(params, state, batch, apply_fn=None):
    """``has_state`` loss contract: returns ``(loss, new_state)``.

    ``batch``: ``{"images": [B,H,W,3], "labels": [B]}``.
    Bind ``apply_fn`` with ``functools.partial(resnet_classification_loss,
    apply_fn=model.apply_fn)`` or a lambda:
    ``lambda p, s, b: resnet_classification_loss(p, s, b, model.apply_fn)``.
    """
    logits, new_state = apply_fn(params, batch["images"], state, train=True)
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    if "loss_mask" in batch:
        mask = batch["loss_mask"].astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0), new_state
    return nll.mean(), new_state
