"""GPT-NeoX decoder (flax.linen): partial rotary, parallel residual,
untied output head.

The reference's big-model-inference benchmark family is GPT-J/GPT-NeoX
(reference: benchmarks/big_model_inference/README.md — the 20B per-token
table); this module gives the zoo that family natively. Architecture per
EleutherAI GPT-NeoX / HF ``GPTNeoXForCausalLM``:

* rotary embedding on the first ``rotary_pct`` of each head's dims, the
  remainder passes through unrotated;
* parallel residual: ``x + attn(ln1(x)) + mlp(ln2(x))`` (one residual
  read, both branches from the same input — the layout GPT-J introduced);
* LayerNorm (with bias), biased projections, untied ``embed_out``.

Same TPU-first conventions as the rest of the zoo: Megatron column/row
``tensor`` splits, activations sharded over ``seq``, attention through
:mod:`accelerate_tpu.ops.attention`, KV-cache decode via
:mod:`accelerate_tpu.ops.kv_cache`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model
from ..ops.fp8 import policy_dot_general as _pdg
from .llama import rope


@dataclasses.dataclass
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 6144
    num_hidden_layers: int = 44
    num_attention_heads: int = 64
    intermediate_size: Optional[int] = None  # defaults to 4*hidden
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    remat: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def neox_20b(cls, **kw) -> "GPTNeoXConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "GPTNeoXConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


GPTNEOX_SHARDING_RULES = [
    (r"embed_in/embedding", P("tensor", None)),
    (r"layer_\d+/attn/(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"layer_\d+/attn/o_proj/kernel", P("tensor", None)),
    (r"layer_\d+/mlp/fc_in/kernel", P(None, "tensor")),
    (r"layer_\d+/mlp/fc_out/kernel", P("tensor", None)),
    (r"embed_out/kernel", P(None, "tensor")),
]

ACTIVATION_SPEC = P(("data", "fsdp"), "seq", None)


def partial_rope(x: jax.Array, positions: jax.Array, theta: float, rotary_dims: int) -> jax.Array:
    """Rotary embedding on the first ``rotary_dims`` of the head dim; the
    tail passes through (GPT-NeoX ``rotary_pct``)."""
    if rotary_dims >= x.shape[-1]:
        return rope(x, positions, theta)
    rotated = rope(x[..., :rotary_dims], positions, theta)
    return jnp.concatenate([rotated, x[..., rotary_dims:]], axis=-1)


class GPTNeoXAttention(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, hidden, positions, decode: bool = False):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        rotary_dims = int(head_dim * cfg.rotary_pct)
        q = nn.Dense(cfg.hidden_size, name="q_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        k = nn.Dense(cfg.hidden_size, name="k_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        v = nn.Dense(cfg.hidden_size, name="v_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)

        def split(x):
            return x.reshape(*x.shape[:-1], cfg.num_attention_heads, head_dim)

        q = partial_rope(split(q), positions, cfg.rope_theta, rotary_dims)
        k = partial_rope(split(k), positions, cfg.rope_theta, rotary_dims)
        v = split(v)
        if decode:
            from ..ops.kv_cache import cached_attention

            out = cached_attention(self, q, k, v, cfg.max_position_embeddings)
        else:
            from ..ops.attention import active_mesh, dot_product_attention

            out = dot_product_attention(q, k, v, causal=True, mesh=active_mesh())
        out = out.reshape(*out.shape[:-2], cfg.hidden_size)
        return nn.Dense(cfg.hidden_size, name="o_proj", dtype=hidden.dtype, dot_general=_pdg())(out)


class GPTNeoXMLP(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        h = nn.Dense(cfg.intermediate_size, name="fc_in", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        h = nn.gelu(h, approximate=False)
        return nn.Dense(cfg.hidden_size, name="fc_out", dtype=hidden.dtype, dot_general=_pdg())(h)


class GPTNeoXBlock(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, hidden, positions, decode: bool = False):
        cfg = self.config
        attn_out = GPTNeoXAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="input_norm", dtype=hidden.dtype)(hidden),
            positions,
            decode,
        )
        if cfg.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x)) — both branches read the same
            # residual stream (GPT-J layout; one residual add, better fusion)
            mlp_out = GPTNeoXMLP(cfg, name="mlp")(
                nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="post_attn_norm", dtype=hidden.dtype)(hidden)
            )
            return hidden + attn_out + mlp_out
        hidden = hidden + attn_out
        return hidden + GPTNeoXMLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="post_attn_norm", dtype=hidden.dtype)(hidden)
        )


class GPTNeoXModel(nn.Module):
    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, decode: bool = False):
        cfg = self.config
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_in")(input_ids)
        if positions is None:
            positions = jnp.arange(input_ids.shape[-1])[None]
        from ..parallel.sharding import maybe_shard

        hidden = maybe_shard(hidden, ACTIVATION_SPEC)

        block = nn.remat(GPTNeoXBlock, prevent_cse=False, static_argnums=(3,)) if cfg.remat else GPTNeoXBlock
        for i in range(cfg.num_hidden_layers):
            hidden = block(cfg, name=f"layer_{i}")(hidden, positions, decode)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="final_norm", dtype=hidden.dtype)(hidden)
        return nn.Dense(cfg.vocab_size, use_bias=False, name="embed_out", dtype=jnp.float32)(hidden)


def create_gptneox_model(config: Optional[GPTNeoXConfig] = None, seed: int = 0, seq_len: int = 64) -> Model:
    config = config or GPTNeoXConfig.tiny()
    module = GPTNeoXModel(config)
    dummy = jnp.zeros((2, seq_len), jnp.int32)
    params = module.init(jax.random.key(seed), dummy)["params"]

    def apply_fn(p, input_ids, positions=None, decode=False, cache=None):
        """decode=True threads the KV cache: pass ``cache`` (or None to
        initialise) and receive ``(logits, new_cache)``."""
        if decode:
            variables = {"params": p}
            if cache is not None:
                variables["cache"] = cache
            logits, mutated = module.apply(variables, input_ids, positions, decode=True, mutable=["cache"])
            return logits, mutated["cache"]
        return module.apply({"params": p}, input_ids, positions)

    model = Model(apply_fn, params, sharding_rules=GPTNEOX_SHARDING_RULES, name="gptneox")
    model.config = config
    model.module = module
    return model
