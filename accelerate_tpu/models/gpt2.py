"""GPT-2 decoder (flax.linen): learned positions, pre-LN, GELU MLP, tied head.

Completes the model-family coverage the reference gets via its Megatron
config parsers — bert/gpt2/t5/llama (reference:
src/accelerate/utils/dataclasses.py:2532-2662 parse_bert_config/gpt2/t5/
llama). Same TPU-first layout conventions as the rest of the zoo:
Megatron column/row ``tensor`` splits, activation sharding over
``seq``, attention dispatched through :mod:`accelerate_tpu.ops.attention`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.fp8 import policy_dot_general as _pdg
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None  # defaults to 4*hidden
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    embd_pdrop: float = 0.1
    tie_word_embeddings: bool = True
    remat: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


GPT2_SHARDING_RULES = [
    (r"wte/embedding", P("tensor", None)),
    (r"layer_\d+/attn/(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"layer_\d+/attn/o_proj/kernel", P("tensor", None)),
    (r"layer_\d+/mlp/fc_in/kernel", P(None, "tensor")),
    (r"layer_\d+/mlp/fc_out/kernel", P("tensor", None)),
    (r"lm_head/kernel", P(None, "tensor")),
]

ACTIVATION_SPEC = P(("data", "fsdp"), "seq", None)


class GPT2Attention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, decode: bool = False):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        q = nn.Dense(cfg.hidden_size, name="q_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        k = nn.Dense(cfg.hidden_size, name="k_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        v = nn.Dense(cfg.hidden_size, name="v_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)

        def split(x):
            return x.reshape(*x.shape[:-1], cfg.num_attention_heads, head_dim)

        if decode:
            from ..ops.kv_cache import cached_attention

            out = cached_attention(self, split(q), split(k), split(v), cfg.max_position_embeddings)
        else:
            from ..ops.attention import active_mesh, dot_product_attention

            out = dot_product_attention(split(q), split(k), split(v), causal=True, mesh=active_mesh())
        out = out.reshape(*out.shape[:-2], cfg.hidden_size)
        return nn.Dense(cfg.hidden_size, name="o_proj", dtype=hidden.dtype, dot_general=_pdg())(out)


class GPT2MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        h = nn.Dense(cfg.intermediate_size, name="fc_in", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        h = nn.gelu(h, approximate=True)
        return nn.Dense(cfg.hidden_size, name="fc_out", dtype=hidden.dtype, dot_general=_pdg())(h)


class GPT2Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, hidden, decode: bool = False):
        cfg = self.config
        hidden = hidden + GPT2Attention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_1", dtype=hidden.dtype)(hidden), decode
        )
        hidden = hidden + GPT2MLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_2", dtype=hidden.dtype)(hidden)
        )
        return hidden


class GPT2Model(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, positions=None, deterministic: bool = True, decode: bool = False):
        cfg = self.config
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="wte")
        hidden = wte(input_ids)
        if positions is None:
            positions = jnp.arange(input_ids.shape[-1])[None]
        hidden = hidden + nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, name="wpe"
        )(positions)
        from ..parallel.sharding import maybe_shard

        hidden = maybe_shard(hidden, ACTIVATION_SPEC)

        block = nn.remat(GPT2Block, prevent_cse=False, static_argnums=(2,)) if cfg.remat else GPT2Block
        for i in range(cfg.num_hidden_layers):
            hidden = block(cfg, name=f"layer_{i}")(hidden, decode)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_f", dtype=hidden.dtype)(hidden)
        if cfg.tie_word_embeddings:
            return hidden.astype(jnp.float32) @ wte.embedding.T.astype(jnp.float32)
        return nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head", dtype=jnp.float32)(hidden)


def create_gpt2_model(config: Optional[GPT2Config] = None, seed: int = 0, seq_len: int = 64) -> Model:
    config = config or GPT2Config.tiny()
    module = GPT2Model(config)
    dummy = jnp.zeros((2, seq_len), jnp.int32)
    params = module.init(jax.random.key(seed), dummy)["params"]

    def apply_fn(p, input_ids, positions=None, decode=False, cache=None):
        """decode=True threads the KV cache: pass ``cache`` (or None to
        initialise) and receive ``(logits, new_cache)``."""
        if decode:
            variables = {"params": p}
            if cache is not None:
                variables["cache"] = cache
            logits, mutated = module.apply(
                variables, input_ids, positions, decode=True, mutable=["cache"]
            )
            return logits, mutated["cache"]
        return module.apply({"params": p}, input_ids, positions)

    model = Model(apply_fn, params, sharding_rules=GPT2_SHARDING_RULES, name="gpt2")
    model.config = config
    model.module = module
    return model
