"""Mixtral-style sparse-MoE decoder: Llama attention + top-k routed experts.

The expert-parallel flagship. Reference has no MoE model support at all
(SURVEY §2.2 EP row: only DeepSpeed MoE leaf-class marking,
utils/dataclasses.py); this model exists to exercise the ``expert`` mesh
axis end-to-end: expert weights sharded one group per expert-axis slice,
token dispatch via GSPMD all-to-all (see ops/moe.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model
from ..ops.moe import MoEBlock
from .llama import LlamaAttention, LlamaConfig, RMSNorm


@dataclasses.dataclass
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 1e6
    router_aux_loss_coef: float = 0.02
    attention_impl: str = "auto"
    # Qwen3-MoE variations through the same machinery: per-head q/k
    # RMSNorm, an explicit head width, a separate expert FF width, and the
    # raw-softmax (non-renormalised) combine weights
    qk_norm: bool = False
    head_dim: Optional[int] = None
    moe_intermediate_size: Optional[int] = None
    norm_topk: bool = True

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 96)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("num_local_experts", 4)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    def as_llama(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            max_position_embeddings=self.max_position_embeddings,
            rms_norm_eps=self.rms_norm_eps,
            rope_theta=self.rope_theta,
            attention_impl=self.attention_impl,
            qk_norm=self.qk_norm,
            head_dim=self.head_dim,
        )


# Attention follows the Llama column/row TP splits; expert weights shard
# their leading dim over ``expert`` and the ff dim over ``tensor``.
MIXTRAL_SHARDING_RULES = [
    (r"embed_tokens/embedding", P("tensor", None)),
    (r"layer_\d+/attn/(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"layer_\d+/attn/o_proj/kernel", P("tensor", None)),
    (r"layer_\d+/moe/experts/(gate|up)_proj", P("expert", None, "tensor")),
    (r"layer_\d+/moe/experts/down_proj", P("expert", "tensor", None)),
    (r"layer_\d+/moe/router/kernel", P(None, None)),
    (r"lm_head/kernel", P(None, "tensor")),
]


class MixtralLayer(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, hidden, positions):
        cfg = self.config
        hidden = hidden + LlamaAttention(cfg.as_llama(), name="attn")(
            RMSNorm(cfg.rms_norm_eps, name="input_norm")(hidden), positions
        )
        hidden = hidden + MoEBlock(
            num_experts=cfg.num_local_experts,
            intermediate_size=cfg.moe_intermediate_size or cfg.intermediate_size,
            num_selected=cfg.num_experts_per_tok,
            capacity_factor=cfg.capacity_factor,
            norm_topk=cfg.norm_topk,
            name="moe",
        )(RMSNorm(cfg.rms_norm_eps, name="post_attn_norm")(hidden))
        return hidden


class MixtralModel(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        hidden = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_tokens")(input_ids)
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[-1]), input_ids.shape)
        from ..parallel.sharding import maybe_shard

        hidden = maybe_shard(hidden, P(("data", "fsdp"), "seq", None))
        for i in range(cfg.num_hidden_layers):
            hidden = MixtralLayer(cfg, name=f"layer_{i}")(hidden, positions)
        hidden = RMSNorm(cfg.rms_norm_eps, name="final_norm")(hidden)
        return nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head", dtype=jnp.float32)(hidden)


def create_mixtral_model(
    config: Optional[MixtralConfig] = None, seed: int = 0, seq_len: int = 128
) -> Model:
    config = config or MixtralConfig.tiny()
    module = MixtralModel(config)
    dummy = jnp.zeros((2, seq_len), jnp.int32)
    params = module.init(jax.random.key(seed), dummy)["params"]

    def apply_fn(p, input_ids):
        return module.apply({"params": p}, input_ids)

    model = Model(apply_fn, params, sharding_rules=MIXTRAL_SHARDING_RULES, name="mixtral")
    model.config = config
    model.module = module
    return model


def mixtral_lm_loss(params, batch, apply_fn=None, module=None, aux_coef: Optional[float] = None):
    """Causal-LM loss + router load-balance aux term (one forward pass:
    aux losses come from the sown intermediates of the same apply).
    ``aux_coef`` defaults to the module config's ``router_aux_loss_coef``."""
    from .llama import causal_lm_loss, next_token_cross_entropy

    if module is None:
        return causal_lm_loss(params, batch, apply_fn)
    if aux_coef is None:
        aux_coef = module.config.router_aux_loss_coef
    logits, inter = module.apply(
        {"params": params}, batch["input_ids"], mutable=["intermediates"]
    )
    loss = next_token_cross_entropy(logits, batch)
    leaves = jax.tree.leaves(inter["intermediates"])
    if leaves:
        loss = loss + aux_coef * sum(jnp.sum(l) for l in leaves) / len(leaves)
    return loss
