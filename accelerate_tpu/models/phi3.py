"""Phi-3: the llama architecture with fused checkpoint projections.

Microsoft's Phi-3 decoders are llama modules in all but the state-dict
layout: attention ships ONE fused ``qkv_proj`` tensor and the MLP one
fused ``gate_up_proj``. Rather than teaching the module about fusion
(XLA fuses the three matmuls regardless — the module split costs
nothing on TPU), the importer splits the fused tensors into the llama
layout (:func:`accelerate_tpu.models.hub.load_hf_phi3`) and everything
else — sharding rules, loss, decode, serving, quantization — is the
llama surface. Mini variants carry a ~2k sliding window, riding the
same band paths as Mistral.

The reference has no in-tree models (SURVEY §2.2); importer parity is
tested against ``transformers.Phi3ForCausalLM`` in
tests/test_hf_parity.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

PHI3_SHARDING_RULES = LLAMA_SHARDING_RULES
Phi3Model = LlamaModel


@dataclasses.dataclass
class Phi3Config(LlamaConfig):
    """Llama config with phi-3-mini-4k defaults (MHA, 2047-token window)."""

    vocab_size: int = 32064
    hidden_size: int = 3072
    intermediate_size: int = 8192
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    sliding_window: Optional[int] = 2047

    @classmethod
    def tiny(cls, **kw) -> "Phi3Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("sliding_window", 8)
        return cls(**kw)

    @classmethod
    def phi3_mini_4k(cls, **kw) -> "Phi3Config":
        return cls(**kw)


def create_phi3_model(config: Optional[Phi3Config] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with Phi-3 widths and window."""
    return create_llama_model(config or Phi3Config.tiny(), seed=seed, seq_len=seq_len)
