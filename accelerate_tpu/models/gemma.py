"""Gemma: the llama skeleton with Google's four deviations.

Gemma decoders differ from llama in exactly the knobs
:class:`~accelerate_tpu.models.llama.LlamaConfig` now carries:

* an explicit ``head_dim`` (256) decoupled from ``hidden / heads`` —
  gemma-2b even runs MQA (1 KV head, 8 query heads);
* GeGLU MLP (tanh-approximated gelu on the gate, ``mlp_activation``);
* RMSNorm stores a zero-centred OFFSET applied as ``1 + scale``
  (``norm_plus_one``) — checkpoints import verbatim;
* embeddings multiplied by ``sqrt(hidden)`` (``scale_embeddings``), and
  the LM head is ALWAYS tied to the embedding table
  (``tie_word_embeddings`` — true weight sharing, not a copy).

The HF state-dict layout is the llama one, so the importer reuses
``convert_hf_llama_state`` — the rope re-pairing derives the head width
from the projection shapes, so the explicit head_dim needs no special
handling. Parity vs ``transformers.GemmaForCausalLM`` in
tests/test_hf_parity.py. The reference has no in-tree models
(SURVEY §2.2); this family is zoo surface.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

GEMMA_SHARDING_RULES = LLAMA_SHARDING_RULES
GemmaModel = LlamaModel


@dataclasses.dataclass
class GemmaConfig(LlamaConfig):
    """Llama config with gemma-2b defaults (MQA, head_dim 256, GeGLU,
    (1+scale) norms, scaled embeddings)."""

    vocab_size: int = 256000
    hidden_size: int = 2048
    intermediate_size: int = 16384
    num_hidden_layers: int = 18
    num_attention_heads: int = 8
    num_key_value_heads: int = 1
    head_dim: Optional[int] = 256
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    mlp_activation: str = "gelu_tanh"
    norm_plus_one: bool = True
    scale_embeddings: bool = True
    tie_word_embeddings: bool = True

    @classmethod
    def tiny(cls, **kw) -> "GemmaConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 1)  # MQA like gemma-2b
        kw.setdefault("head_dim", 32)  # != hidden/heads on purpose
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    @classmethod
    def gemma_2b(cls, **kw) -> "GemmaConfig":
        return cls(**kw)

    @classmethod
    def gemma_7b(cls, **kw) -> "GemmaConfig":
        kw.setdefault("hidden_size", 3072)
        kw.setdefault("intermediate_size", 24576)
        kw.setdefault("num_hidden_layers", 28)
        kw.setdefault("num_attention_heads", 16)
        kw.setdefault("num_key_value_heads", 16)
        return cls(**kw)


def create_gemma_model(config: Optional[GemmaConfig] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with Gemma's head width, GeGLU, (1+scale) norms and scaled embeddings."""
    return create_llama_model(config or GemmaConfig.tiny(), seed=seed, seq_len=seq_len)
