"""Mistral: the llama architecture + sliding-window attention.

Mistral-7B is structurally llama (RMSNorm pre-norm, rotary, SwiGLU,
GQA) with one semantic change — every position attends to at most the
last ``sliding_window`` keys — plus different default widths (14336
intermediate, 8 KV heads; rope theta 1e4 for v0.1, 1e6 for v0.2/v0.3).
The family therefore reuses
:mod:`accelerate_tpu.models.llama` wholesale: :class:`MistralConfig`
subclasses :class:`LlamaConfig` (the ``sliding_window`` field lives
there so the band mask threads through the shared attention, KV-cache,
and paged-cache paths), and the module/sharding/loss/quantization
surfaces are the llama ones.

The reference has no in-tree models (it delegates to transformers,
SURVEY §2.2/hard-part #3); importer parity is tested against
``transformers.MistralForCausalLM`` in tests/test_hf_parity.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

MISTRAL_SHARDING_RULES = LLAMA_SHARDING_RULES
MistralModel = LlamaModel


@dataclasses.dataclass
class MistralConfig(LlamaConfig):
    """Llama config with Mistral-7B-v0.1 defaults: 32k context with a
    4096-token window, theta 1e4. v0.2/v0.3 dropped the window and
    raised theta — use :meth:`mistral_7b_v3` for those checkpoints (the
    wrong variant means wrong rotary angles or a spurious band mask)."""

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    sliding_window: Optional[int] = 4096

    @classmethod
    def tiny(cls, **kw) -> "MistralConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("sliding_window", 8)
        return cls(**kw)

    @classmethod
    def mistral_7b_v1(cls, **kw) -> "MistralConfig":
        """Mistral-7B-v0.1: theta 1e4, sliding window 4096."""
        return cls(**kw)

    @classmethod
    def mistral_7b_v3(cls, **kw) -> "MistralConfig":
        """Mistral-7B-v0.2/v0.3: theta 1e6, NO sliding window (the v0.2
        change); v0.3 only grew the vocab for tool tokens."""
        kw.setdefault("vocab_size", 32768)
        kw.setdefault("rope_theta", 1e6)
        kw.setdefault("sliding_window", None)
        return cls(**kw)


def create_mistral_model(config: Optional[MistralConfig] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with the Mistral band mask (config.sliding_window)."""
    return create_llama_model(config or MistralConfig.tiny(), seed=seed, seq_len=seq_len)
