"""Llama-family decoder (flax.linen): RMSNorm, RoPE, GQA, SwiGLU.

The scale-out model for the framework (the reference's FSDP2 benchmark
fine-tunes Llama-2-7B — BASELINE.json configs). TPU-first choices:

* sharding rules for the full 4D layout (fsdp x tensor x seq x data):
  Megatron column/row splits over ``tensor``, sequence-dim activation
  sharding constraint over ``seq`` (Megatron-SP equivalent);
* ``lax.scan`` over layers (``scan_layers=True``) so trace/compile time is
  O(1) in depth — the TPU answer to the reference's "regional compilation"
  (reference: utils/other.py:101-172 compile_regions, SURVEY §2.6);
* attention dispatches to flash/blockwise for long sequences.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.fp8 import policy_dot_general as _pdg
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    # HF-style rope_scaling dict ({"rope_type": "llama3"|"linear"|"yarn"|
    # "longrope", "factor": ..., ...}); Llama-3.1/3.2 checkpoints require
    # the llama3 rescale
    rope_scaling: Optional[dict] = None
    # HF keeps this at the config top level for Phi-3 longrope checkpoints;
    # mirrors config.json's original_max_position_embeddings
    original_max_position_embeddings: Optional[int] = None
    scan_layers: bool = True
    remat: bool = True
    # "auto": ring attention when the mesh seq axis is non-trivial, else
    # dense/flash; "ring" | "all_to_all" | "dense" force a path.
    attention_impl: str = "auto"
    # Mistral-style sliding-window attention: each position attends to at
    # most the last `sliding_window` keys (itself included). None = full
    # causal. Short sequences mask the band in XLA; flash-length TPU
    # sequences run the banded flash kernel (O(S*W)); seq-sharded meshes
    # apply the band inside ring / all-to-all context parallelism.
    sliding_window: Optional[int] = None
    # Qwen2-style bias on the q/k/v projections only (o_proj stays
    # bias-free); importer re-pairs q/k biases for the rope convention
    qkv_bias: bool = False
    # Qwen3-style per-head RMSNorm on q and k (one [head_dim] scale
    # shared across heads, applied after the projection, before rope);
    # the importer re-pairs the scales for the interleaved rope layout
    qk_norm: bool = False
    # OLMo2-style FULL-WIDTH RMSNorm on the flat q/k projections
    # ([H*head_dim] / [H_kv*head_dim] scales, applied before the head
    # reshape); mutually exclusive with qk_norm
    qk_norm_flat: bool = False
    # OLMo2-style post-norms: normalize each sublayer's output before the
    # residual add instead of its input (no input_norm params)
    norm_after: bool = False
    # Gemma2-style sandwich norms: BOTH a pre- and post-norm around each
    # sublayer (input_norm/post_attn_norm around attention,
    # pre_ffn_norm/post_ffn_norm around the MLP)
    sandwich_norm: bool = False
    # Gemma2 logit softcapping: tanh-bound attention scores / final logits
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    # Gemma2: attention scale = query_pre_attn_scalar**-0.5 when set
    # (instead of head_dim**-0.5)
    query_pre_attn_scalar: Optional[float] = None
    # per-layer attention kind ("sliding_attention"|"full_attention") for
    # Gemma2's alternating local/global layers — requires scan_layers=False
    # (a scanned block shares one static config across layers)
    layer_types: Optional[tuple] = None
    # Gemma3: sliding layers rotate with THIS theta (10k) and no rope
    # scaling, while full layers use rope_theta (1M) + rope_scaling
    rope_local_theta: Optional[float] = None
    # Gemma-family knobs: an explicit per-head width (None = hidden/heads),
    # the MLP gate activation, RMSNorm's (1 + scale) variant, and the
    # sqrt(hidden) embedding multiplier
    head_dim: Optional[int] = None
    mlp_activation: str = "silu"  # silu | gelu_tanh
    norm_plus_one: bool = False
    scale_embeddings: bool = False
    # share the embedding table with the LM head (Gemma always; small
    # Qwen2 variants): no separate lm_head param exists, so fine-tuning
    # cannot drift the two apart and the 256k-vocab table isn't duplicated
    tie_word_embeddings: bool = False
    # weight-only quantized block projections (int8|int4|nf4): every
    # q/k/v/o/gate/up/down kernel becomes a QuantDense whose packed codes
    # are the params — the decode-bandwidth win (set via
    # ``load_and_quantize_model``, not by hand)
    quant_method: Optional[str] = None
    quant_group_size: Optional[int] = None

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


# Megatron column/row splits over ``tensor``. Two path layouts exist:
# scan_layers=True stacks per-layer weights with a leading layer dim under
# ``layers/block/...`` (specs start with None for the scan dim);
# scan_layers=False names layers ``layer_<i>/...``. Anchored so neither
# rule set can match the other layout's paths.
LLAMA_SHARDING_RULES = [
    (r"embed_tokens/embedding", P("tensor", None)),
    # stacked (scan) variants: [L, in, out]-shaped kernels
    (r"layers/block/attn/(q|k|v)_proj/kernel", P(None, None, "tensor")),
    (r"layers/block/attn/(q|k|v)_proj/bias", P(None, "tensor")),
    (r"layers/block/attn/o_proj/kernel", P(None, "tensor", None)),
    (r"layers/block/mlp/(gate|up)_proj/kernel", P(None, None, "tensor")),
    (r"layers/block/mlp/down_proj/kernel", P(None, "tensor", None)),
    (r"lm_head/kernel", P(None, "tensor")),
    # unstacked variants (scan_layers=False): [in, out]-shaped kernels
    (r"layer_\d+/attn/(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"layer_\d+/attn/(q|k|v)_proj/bias", P("tensor")),
    (r"layer_\d+/attn/o_proj/kernel", P("tensor", None)),
    (r"layer_\d+/mlp/(gate|up)_proj/kernel", P(None, "tensor")),
    (r"layer_\d+/mlp/down_proj/kernel", P("tensor", None)),
]

# Quantized variants: qdata/qscale are [*, n_groups, g(, packed), out] with
# a leading layer dim when stacked — column-parallel splits the trailing
# out dim; row-parallel splits the group dim of qdata and replicates the
# scales (the per-channel scale commutes with the contraction psum).
LLAMA_SHARDING_RULES += [
    (r"layers/block/(attn/(q|k|v)_proj|mlp/(gate|up)_proj)/(qdata|qscale)", P(None, None, None, "tensor")),
    (r"layers/block/(attn/o_proj|mlp/down_proj)/qdata", P(None, None, "tensor", None)),
    (r"layers/block/(attn/o_proj|mlp/down_proj)/qscale", P(None, None, None, None)),
    (r"layer_\d+/(attn/(q|k|v)_proj|mlp/(gate|up)_proj)/(qdata|qscale)", P(None, None, "tensor")),
    (r"layer_\d+/(attn/o_proj|mlp/down_proj)/qdata", P(None, "tensor", None)),
    (r"layer_\d+/(attn/o_proj|mlp/down_proj)/qscale", P(None, None, None)),
]

# Activation sharding (Megatron-SP equivalent): token dim over ``seq``.
ACTIVATION_SPEC = P(("data", "fsdp"), "seq", None)


def _dense(cfg: "LlamaConfig", features: int, name: str, dtype, use_bias: bool = False):
    """Block projection factory: plain Dense, QuantDense when the config
    carries a weight-only quantization method, or FP8Dense when the active
    precision policy requests the delayed-scaling fp8 recipe (amax
    histories in the ``fp8`` collection -> ``model.state``)."""
    if cfg.quant_method is not None:
        from ..ops.qdense import QuantDense

        return QuantDense(
            features, method=cfg.quant_method, group_size=cfg.quant_group_size, dtype=dtype,
            name=name, use_bias=use_bias,
        )
    from ..ops.fp8 import FP8Dense, fp8_recipe

    recipe = fp8_recipe()
    if recipe is not None and recipe.delayed_scaling:
        if use_bias:
            raise NotImplementedError("FP8Dense (delayed scaling) has no bias; qkv_bias models need the bf16 path")
        return FP8Dense(
            features,
            name=name,
            dtype=dtype,
            amax_history_len=recipe.amax_history_len,
            amax_compute_algo=recipe.amax_compute_algo,
            margin=recipe.margin,
        )
    return nn.Dense(features, use_bias=use_bias, name=name, dtype=dtype, dot_general=_pdg())


class RMSNorm(nn.Module):
    eps: float = 1e-5
    # Gemma convention: zero-initialised param applied as (1 + scale) —
    # checkpoints store the OFFSET, so the importer maps weights verbatim
    plus_one: bool = False

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.zeros if self.plus_one else nn.initializers.ones
        scale = self.param("scale", init, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        if self.plus_one:
            # Gemma keeps normalize AND (1 + scale) in fp32, casting only
            # the result — matching HF's rounding so bf16 runs agree
            out = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps) * (1.0 + scale.astype(jnp.float32))
            return out.astype(x.dtype)
        # llama convention: cast the normalized stream first, multiply in
        # the stream dtype (HF LlamaRMSNorm order)
        normed = (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)
        return normed * scale.astype(x.dtype)


def rope_frequencies(
    d: int,
    theta: float,
    scaling: Optional[dict] = None,
    *,
    max_pos: Optional[int] = None,
    seq_len: Optional[int] = None,
    orig_max: Optional[int] = None,
) -> tuple[jax.Array, float]:
    """``(inverse frequencies, attention factor)`` for rotary embedding,
    with HF-style ``rope_scaling`` applied (reference behavior: the
    reference delegates models to ``transformers``, whose
    ``ROPE_INIT_FUNCTIONS`` implement these; Llama-3.1/3.2 checkpoints
    REQUIRE the ``llama3`` rescale or every rotary angle is wrong at every
    position). The attention factor multiplies cos/sin (1.0 except
    yarn/longrope).

    Supported ``rope_type``s: ``default``; ``linear`` (position
    interpolation: all frequencies / factor); ``llama3`` (piecewise
    wavelength-dependent rescale with smooth interpolation band); ``yarn``
    (NTK-by-parts ramp between interpolated and extrapolated frequencies,
    mscale attention factor — DeepSeek/Qwen long-context); ``longrope``
    (per-dimension short/long factor tables — Phi-3 128k; ``seq_len``, a
    STATIC python int, selects the table like HF does from the runtime
    length). Others (``dynamic`` NTK) raise rather than silently
    mis-rotate.

    longrope deployment contract (static shapes, unlike HF's per-forward
    dynamic switch): plain forwards select by the input length; EVERY
    cached-decode call — prefill included, generation.py always primes the
    cache with ``decode=True`` — selects by the cache capacity
    (``max_position_embeddings``), so one session never mixes rotary
    tables. Deploying a 128k longrope checkpoint for short sessions?
    Set ``max_position_embeddings`` to the session bound (e.g. 4096) and
    the short table applies, matching HF for sub-original lengths — this
    is also the knob Phi-3's own model card prescribes."""
    import math

    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    if not scaling:
        return freqs, 1.0
    rope_type = scaling.get("rope_type", scaling.get("type", "default"))
    if rope_type == "default":
        return freqs, 1.0
    if rope_type == "linear":
        return freqs / float(scaling["factor"]), 1.0
    if rope_type == "llama3":
        factor = float(scaling["factor"])
        low_freq_factor = float(scaling.get("low_freq_factor", 1.0))
        high_freq_factor = float(scaling.get("high_freq_factor", 4.0))
        orig = float(scaling.get("original_max_position_embeddings", 8192))
        low_freq_wavelen = orig / low_freq_factor
        high_freq_wavelen = orig / high_freq_factor
        wavelen = 2.0 * jnp.pi / freqs
        # long wavelengths fully scaled, short ones untouched, the band
        # between interpolated (HF _compute_llama3_parameters)
        smooth = (orig / wavelen - low_freq_factor) / (high_freq_factor - low_freq_factor)
        smoothed = (1.0 - smooth) * freqs / factor + smooth * freqs
        scaled = jnp.where(wavelen > low_freq_wavelen, freqs / factor, smoothed)
        return jnp.where(wavelen < high_freq_wavelen, freqs, scaled), 1.0
    if rope_type == "yarn":
        factor = float(scaling["factor"])
        orig = float(scaling.get("original_max_position_embeddings") or orig_max or max_pos or 0)
        if not orig:
            raise ValueError("yarn rope_scaling needs original_max_position_embeddings or max_pos")
        attention_factor = scaling.get("attention_factor")
        mscale, mscale_all_dim = scaling.get("mscale"), scaling.get("mscale_all_dim")

        def get_mscale(scale, m=1.0):
            return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

        if attention_factor is None:
            if mscale and mscale_all_dim:
                attention_factor = get_mscale(factor, mscale) / get_mscale(factor, mscale_all_dim)
            else:
                attention_factor = get_mscale(factor)
        beta_fast = scaling.get("beta_fast") or 32
        beta_slow = scaling.get("beta_slow") or 1

        def correction_dim(num_rotations):
            return d * math.log(orig / (num_rotations * 2 * math.pi)) / (2 * math.log(theta))

        low, high = correction_dim(beta_fast), correction_dim(beta_slow)
        if scaling.get("truncate", True):
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, d - 1)
        if low == high:
            high += 0.001  # HF's singularity guard
        ramp = jnp.clip((jnp.arange(d // 2, dtype=jnp.float32) - low) / (high - low), 0, 1)
        extrapolation_factor = 1.0 - ramp
        inv = freqs / factor * (1 - extrapolation_factor) + freqs * extrapolation_factor
        return inv, float(attention_factor)
    if rope_type == "dynamic":
        # dynamic NTK: the base grows with the deployed length so the
        # longest wavelength always spans it (HF _compute_dynamic_ntk_
        # parameters with seq_len pinned to the static deployment length —
        # HF recomputes per forward, we specialize per compiled shape)
        factor = float(scaling["factor"])
        # NO max_pos fallback here: orig == deployed bound makes the formula
        # cancel to base == theta — the scaling silently disabled exactly
        # when the user relied on the guess (unlike yarn, where a wrong
        # orig at least changes the numbers)
        orig = float(scaling.get("original_max_position_embeddings") or orig_max or 0)
        if not orig:
            raise ValueError(
                "dynamic rope_scaling needs the ORIGINAL context length — put "
                "original_max_position_embeddings in the rope_scaling dict or set "
                "LlamaConfig.original_max_position_embeddings (HF stores it as the "
                "checkpoint's top-level max_position_embeddings)"
            )
        length = float(max(seq_len or 0, orig))
        base = theta * ((factor * length / orig) - (factor - 1)) ** (d / (d - 2))
        return 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)), 1.0
    if rope_type == "longrope":
        # HF's config.json stores original_max_position_embeddings at the
        # TOP level for Phi-3; accept it inside the dict or via orig_max,
        # and refuse to guess — a silent max_pos fallback would pin the
        # short table forever with attention factor 1.0
        orig = int(scaling.get("original_max_position_embeddings") or orig_max or 0)
        if not orig:
            raise ValueError(
                "longrope rope_scaling needs original_max_position_embeddings — put it in "
                "the rope_scaling dict or set LlamaConfig.original_max_position_embeddings "
                "(HF config.json keeps it at the top level)"
            )
        factor = scaling.get("factor")
        if max_pos:
            factor = max_pos / orig
        attention_factor = scaling.get("attention_factor")
        if attention_factor is None:
            attention_factor = (
                1.0 if not factor or factor <= 1.0 else math.sqrt(1 + math.log(factor) / math.log(orig))
            )
        use_long = seq_len is not None and seq_len > orig
        ext = jnp.asarray(scaling["long_factor" if use_long else "short_factor"], jnp.float32)
        return freqs / ext, float(attention_factor)
    raise NotImplementedError(
        f"rope_scaling type {rope_type!r} is not supported "
        "(default/linear/llama3/yarn/longrope/dynamic are); "
        "a silent fallback would mis-rotate every position"
    )


def rope(
    x: jax.Array,
    positions: jax.Array,
    theta: float,
    scaling: Optional[dict] = None,
    *,
    max_pos: Optional[int] = None,
    seq_len: Optional[int] = None,
    orig_max: Optional[int] = None,
) -> jax.Array:
    """Rotary embedding over the last dim of [B, S, H, D]."""
    d = x.shape[-1]
    freqs, attn_factor = rope_frequencies(
        d, theta, scaling, max_pos=max_pos, seq_len=seq_len, orig_max=orig_max
    )
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos, sin = jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]
    if attn_factor != 1.0:
        cos, sin = cos * attn_factor, sin * attn_factor
    x1, x2 = x[..., ::2], x[..., 1::2]
    rotated = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


def _dispatch_attention(
    q, k, v, impl: str, sliding_window: Optional[int] = None, scale=None, logit_softcap=None
):
    """Pick the attention path: context-parallel (ring / all-to-all) when
    the active mesh has a non-trivial ``seq`` axis, else dense/flash. This
    is where long-context becomes a *layout* decision rather than a model
    rewrite (SURVEY §5). ``sliding_window`` adds a Mistral-style band on
    EVERY path: the XLA mask at short lengths, the banded flash kernel
    (O(S*W)) at flash lengths on TPU, and absolute-position masking
    inside the ring / all-to-all schedules on seq-sharded meshes."""
    if impl not in ("auto", "ring", "all_to_all", "dense"):
        raise ValueError(f"attention_impl must be auto|ring|all_to_all|dense, got {impl!r}")
    mesh = None
    if impl != "dense":
        from ..ops.attention import active_mesh

        mesh = active_mesh()
    seq_ok = mesh is not None and "seq" in mesh.shape and mesh.shape["seq"] > 1
    if impl in ("ring", "all_to_all") and not seq_ok:
        # an explicit request must not silently fall back to the O(S^2) path
        raise ValueError(
            f"attention_impl={impl!r} requires an active mesh with a seq axis > 1 "
            f"(got {dict(mesh.shape) if mesh is not None else None}); use 'auto' for adaptive dispatch"
        )
    if seq_ok:
        from ..parallel.context import context_parallel_attention

        if logit_softcap is not None:
            raise NotImplementedError(
                "attention logit softcapping (Gemma2) is not supported inside the "
                "ring/all-to-all context-parallel schedules; use a mesh without a seq axis"
            )
        method = "all_to_all" if impl == "all_to_all" else "ring"
        return context_parallel_attention(
            q, k, v, mesh=mesh, causal=True, method=method, window=sliding_window, scale=scale
        )
    from ..ops.attention import dot_product_attention

    # the op folds the band (if any) into the XLA mask at short lengths
    # and runs the banded flash kernel (O(S*W)) at flash lengths on TPU
    # (the op's auto-dispatch avoids the flash kernel when a softcap is
    # set — the kernel has no tanh-cap branch)
    return dot_product_attention(
        q, k, v, causal=True, mesh=mesh, window=sliding_window, scale=scale,
        logit_softcap=logit_softcap,
    )


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, positions, decode: bool = False):
        cfg = self.config
        head_dim = cfg.head_dim or cfg.hidden_size // cfg.num_attention_heads
        q = _dense(cfg, cfg.num_attention_heads * head_dim, "q_proj", hidden.dtype, cfg.qkv_bias)(hidden)
        k = _dense(cfg, cfg.num_key_value_heads * head_dim, "k_proj", hidden.dtype, cfg.qkv_bias)(hidden)
        v = _dense(cfg, cfg.num_key_value_heads * head_dim, "v_proj", hidden.dtype, cfg.qkv_bias)(hidden)
        if cfg.qk_norm_flat:
            # OLMo2: RMSNorm over the FLAT projection (all heads jointly)
            # before the head split — a different statistic than per-head
            q = RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="q_norm")(q)
            k = RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="k_norm")(k)
        q = q.reshape(*q.shape[:-1], cfg.num_attention_heads, head_dim)
        k = k.reshape(*k.shape[:-1], cfg.num_key_value_heads, head_dim)
        v = v.reshape(*v.shape[:-1], cfg.num_key_value_heads, head_dim)
        if cfg.qk_norm:
            # per-head RMSNorm over head_dim (Qwen3): the mean-of-squares is
            # permutation-invariant, so the interleaved rope layout only
            # requires the imported scale vector to be re-paired (hub.py)
            q = RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="q_norm")(q)
            k = RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="k_norm")(k)
        # longrope's short/long table selection needs a STATIC length hint:
        # prefill uses the (static) input length like HF's runtime switch;
        # decode sees S=1, so the cache capacity stands in for it
        rope_len = cfg.max_position_embeddings if decode else hidden.shape[1]
        q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling,
                 max_pos=cfg.max_position_embeddings, seq_len=rope_len,
                 orig_max=cfg.original_max_position_embeddings)
        k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling,
                 max_pos=cfg.max_position_embeddings, seq_len=rope_len,
                 orig_max=cfg.original_max_position_embeddings)
        scale = None  # attention default: head_dim**-0.5
        if cfg.query_pre_attn_scalar is not None:
            scale = float(cfg.query_pre_attn_scalar) ** -0.5  # Gemma2
        if decode:
            out = self._cached_attention(q, k, v, scale)
        else:
            out = _dispatch_attention(
                q, k, v, cfg.attention_impl, cfg.sliding_window,
                scale=scale, logit_softcap=cfg.attn_logit_softcap,
            )
        out = out.reshape(*out.shape[:-2], cfg.num_attention_heads * head_dim)
        return _dense(cfg, cfg.hidden_size, "o_proj", hidden.dtype)(out)

    def _cached_attention(self, q, k, v, scale=None):
        """KV-cache incremental attention (generation path; shared cache
        machinery in :mod:`accelerate_tpu.ops.kv_cache`)."""
        from ..ops.kv_cache import cached_attention

        return cached_attention(
            self, q, k, v, self.config.max_position_embeddings,
            scale=scale,
            sliding_window=self.config.sliding_window,
            logit_softcap=self.config.attn_logit_softcap,
        )


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        gate = _dense(cfg, cfg.intermediate_size, "gate_proj", hidden.dtype)(hidden)
        up = _dense(cfg, cfg.intermediate_size, "up_proj", hidden.dtype)(hidden)
        if cfg.mlp_activation == "silu":
            act = nn.silu(gate)
        elif cfg.mlp_activation == "gelu_tanh":
            act = nn.gelu(gate, approximate=True)
        else:
            raise ValueError(f"mlp_activation must be silu|gelu_tanh, got {cfg.mlp_activation!r}")
        return _dense(cfg, cfg.hidden_size, "down_proj", hidden.dtype)(act * up)


class LlamaLayer(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, positions, decode: bool = False):
        cfg = self.config
        if cfg.norm_after:
            # OLMo2 convention: normalize each sublayer's OUTPUT before the
            # residual add (no input norms); HF key post_attention_layernorm
            # maps to post_attn_norm, post_feedforward_layernorm to
            # post_ffn_norm
            hidden = hidden + RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="post_attn_norm")(
                LlamaAttention(cfg, name="attn")(hidden, positions, decode)
            )
            hidden = hidden + RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="post_ffn_norm")(
                LlamaMLP(cfg, name="mlp")(hidden)
            )
            return hidden
        if cfg.sandwich_norm:
            # Gemma2 convention: pre- AND post-norm around each sublayer
            hidden = hidden + RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="post_attn_norm")(
                LlamaAttention(cfg, name="attn")(
                    RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="input_norm")(hidden),
                    positions, decode,
                )
            )
            hidden = hidden + RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="post_ffn_norm")(
                LlamaMLP(cfg, name="mlp")(
                    RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="pre_ffn_norm")(hidden)
                )
            )
            return hidden
        hidden = hidden + LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="input_norm")(hidden), positions, decode
        )
        hidden = hidden + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="post_attn_norm")(hidden)
        )
        return hidden


class _ScanLayer(nn.Module):
    """scan-compatible wrapper: carry-in/carry-out signature."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden, positions, decode: bool = False):
        return LlamaLayer(self.config, name="block")(hidden, positions, decode), None


class LlamaModel(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, decode: bool = False):
        cfg = self.config
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_tokens")
        hidden = embed(input_ids)
        if cfg.scale_embeddings:
            # Gemma multiplies embeddings by sqrt(hidden); the constant is
            # cast to the stream dtype FIRST (HF casts to bf16 there, and
            # matching the rounding keeps fp32 parity tests exact)
            hidden = hidden * jnp.asarray(cfg.hidden_size**0.5, hidden.dtype)
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[-1]), input_ids.shape)
        # constrain activations onto the mesh (seq axis = Megatron-SP)
        from ..parallel.sharding import maybe_shard

        hidden = maybe_shard(hidden, ACTIVATION_SPEC)

        if cfg.layer_types is not None and cfg.scan_layers:
            raise ValueError(
                "layer_types (per-layer sliding/full attention, Gemma2) requires "
                "scan_layers=False — a scanned block shares one static config"
            )
        if cfg.layer_types is not None and len(cfg.layer_types) != cfg.num_hidden_layers:
            raise ValueError(
                f"layer_types has {len(cfg.layer_types)} entries for "
                f"{cfg.num_hidden_layers} layers"
            )
        if cfg.scan_layers:
            layer_cls = nn.remat(_ScanLayer, prevent_cse=False, static_argnums=(3,)) if cfg.remat else _ScanLayer
            scanned = nn.scan(
                layer_cls,
                variable_axes={"params": 0, "cache": 0, "fp8": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            hidden, _ = scanned(cfg, name="layers")(hidden, positions, decode)
        else:
            layer_cls = nn.remat(LlamaLayer, prevent_cse=False, static_argnums=(3,)) if cfg.remat else LlamaLayer
            for i in range(cfg.num_hidden_layers):
                lcfg = cfg
                if cfg.layer_types is not None:
                    # Gemma2/3 alternating local/global attention: the band
                    # only applies on "sliding_attention" layers, which in
                    # Gemma3 also rotate with the LOCAL theta and no scaling
                    windowed = cfg.layer_types[i] == "sliding_attention"
                    overrides = {"sliding_window": cfg.sliding_window if windowed else None}
                    if windowed and cfg.rope_local_theta is not None:
                        overrides["rope_theta"] = cfg.rope_local_theta
                        overrides["rope_scaling"] = None
                    lcfg = dataclasses.replace(cfg, **overrides)
                hidden = layer_cls(lcfg, name=f"layer_{i}")(hidden, positions, decode)
        hidden = RMSNorm(cfg.rms_norm_eps, cfg.norm_plus_one, name="final_norm")(hidden)
        if cfg.tie_word_embeddings:
            # true weight tying: reuse the embedding table (no lm_head
            # param at all), matching HF tied-head semantics under
            # fine-tuning and halving the head+table HBM
            logits = hidden.astype(jnp.float32) @ embed.embedding.astype(jnp.float32).T
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head", dtype=jnp.float32)(hidden)
        if cfg.final_logit_softcap is not None:
            from ..ops.attention import softcap

            logits = softcap(logits, cfg.final_logit_softcap)
        return logits


def _wrap_llama(module: LlamaModel, params, config: LlamaConfig, state=None) -> Model:
    def apply_fn(p, input_ids, positions=None, decode=False, cache=None, state=None):
        """decode=True threads the KV cache: pass ``cache`` (or None to
        initialise) and receive ``(logits, new_cache)``. ``state`` threads
        non-param collections (the fp8 amax histories): returns
        ``(logits, new_state)``."""
        if decode:
            variables = {"params": p, **(state or {})}
            if cache is not None:
                variables["cache"] = cache
            # non-param collections (fp8 amax histories) must be mutable
            # too — their per-step updates are discarded during decode
            logits, mutated = module.apply(
                variables, input_ids, positions, True, mutable=["cache", *(state or {})]
            )
            return logits, mutated["cache"]
        if state:
            variables = {"params": p, **state}
            logits, new_state = module.apply(variables, input_ids, positions, mutable=list(state.keys()))
            return logits, dict(new_state)
        return module.apply({"params": p}, input_ids, positions)

    model = Model(apply_fn, params, sharding_rules=LLAMA_SHARDING_RULES, name="llama")
    model.config = config
    model.module = module
    model.state = state
    return model


def create_llama_model(config: Optional[LlamaConfig] = None, seed: int = 0, seq_len: int = 128) -> Model:
    config = config or LlamaConfig.tiny()
    module = LlamaModel(config)
    dummy = jnp.zeros((2, seq_len), jnp.int32)
    variables = module.init(jax.random.key(seed), dummy)
    params = variables["params"]
    state = {k: v for k, v in variables.items() if k != "params"} or None
    return _wrap_llama(module, params, config, state=state)


def causal_lm_loss_state(params, state, batch, apply_fn):
    """:func:`causal_lm_loss` for stateful models (fp8 delayed scaling):
    ``build_train_step(has_state=True)`` contract — returns
    ``(loss, new_state)``."""
    logits, new_state = apply_fn(params, batch["input_ids"], state=state)
    return next_token_cross_entropy(logits, batch), new_state


_PROJ_RE = re.compile(r"^(q|k|v|o|gate|up|down)_proj$")


def quantize_llama_model(model: Model, qconfig=None) -> Model:
    """Weight-only quantize every block projection of a llama :class:`Model`
    into the in-scan :class:`~accelerate_tpu.ops.qdense.QuantDense` layout.

    Unlike the generic wrap-and-dequantize fallback (which materialises the
    full-precision stack outside the layer scan), the packed codes here ARE
    the params, so per-decode-step HBM traffic is the int8/int4 bytes —
    the TPU analogue of the reference's bnb layer replacement
    (reference: src/accelerate/utils/bnb.py:276-373).
    """
    from ..utils.quantization import QuantizationConfig, quantize

    qcfg = qconfig or QuantizationConfig()
    if model.config.quant_method is not None:
        # re-quantizing would find no 'kernel' leaves, rewrite quant_method,
        # and silently reinterpret the packed codes under the new decoder
        raise ValueError(
            f"model is already quantized ({model.config.quant_method}); "
            "quantize the original float model instead"
        )
    new_cfg = dataclasses.replace(model.config, quant_method=qcfg.method, quant_group_size=qcfg.group_size)

    def convert(tree):
        if not hasattr(tree, "items"):
            return tree
        out = {}
        for k, v in tree.items():
            if hasattr(v, "items") and _PROJ_RE.match(k) and "kernel" in v:
                qt = quantize(jnp.asarray(v["kernel"]), qcfg)
                out[k] = {"qdata": qt.data, "qscale": qt.scale}
            else:
                out[k] = convert(v)
        return out

    return _wrap_llama(LlamaModel(new_cfg), convert(model.params), new_cfg)


def causal_lm_loss(params, batch, apply_fn):
    """Next-token cross entropy; labels = input shifted left, padding via
    ``loss_mask``. When labels are auto-derived, the final position (whose
    target would be fabricated) is masked out."""
    return next_token_cross_entropy(apply_fn(params, batch["input_ids"]), batch)


def next_token_cross_entropy(logits, batch):
    """The CE part of :func:`causal_lm_loss`, for callers that already have
    logits (e.g. MoE losses that need the same forward's aux outputs)."""
    mask = batch.get("loss_mask")
    if "labels" in batch:
        labels = batch["labels"]
    else:
        labels = jnp.pad(batch["input_ids"][:, 1:], ((0, 0), (0, 1)))
        last_pos = jnp.zeros(labels.shape, bool).at[:, -1].set(True)
        mask = jnp.where(last_pos, 0.0, 1.0 if mask is None else mask)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
