"""Qwen3: the llama architecture + per-head q/k RMSNorm.

Qwen3 decoders are structurally llama (RMSNorm pre-norm, rotary, GQA,
SwiGLU) with two changes vs Qwen2: the q/k/v biases are GONE, replaced by
a per-head RMSNorm on q and k (``LlamaConfig.qk_norm`` — one ``[head_dim]``
scale shared across heads, applied after the projection, before rope), and
an explicit ``head_dim`` (128) decoupled from ``hidden_size / num_heads``.
Small variants tie the LM head to the embeddings (importer fallback).

Like :mod:`.qwen2`, the module/sharding/loss surfaces are the llama ones;
only the config and checkpoint importer differ. The reference has no
in-tree models (SURVEY §2.2); importer parity is tested against
``transformers.Qwen3ForCausalLM`` in tests/test_hf_parity.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

QWEN3_SHARDING_RULES = LLAMA_SHARDING_RULES
Qwen3Model = LlamaModel


@dataclasses.dataclass
class Qwen3Config(LlamaConfig):
    """Llama config with Qwen3-8B defaults (qk-norm on, explicit head_dim)."""

    vocab_size: int = 151936
    hidden_size: int = 4096
    intermediate_size: int = 12288
    num_hidden_layers: int = 36
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    head_dim: Optional[int] = 128
    max_position_embeddings: int = 40960
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    qk_norm: bool = True

    @classmethod
    def tiny(cls, **kw) -> "Qwen3Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    @classmethod
    def qwen3_8b(cls, **kw) -> "Qwen3Config":
        return cls(**kw)


def create_qwen3_model(config: Optional[Qwen3Config] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with Qwen3's per-head q/k norms."""
    return create_llama_model(config or Qwen3Config.tiny(), seed=seed, seq_len=seq_len)
