"""BERT encoder (flax.linen) — the flagship benchmark model.

The reference framework is model-agnostic but its headline benchmark is
BERT-base on GLUE/MRPC (reference: examples/nlp_example.py, the
BASELINE.json metric). This is a from-scratch TPU-first implementation:

* weights laid out for the mesh: attention/FFN kernels carry ``tensor``-axis
  sharding rules (Megatron column->row split), embeddings shard vocab over
  ``tensor``, everything FSDP-shardable via the auto rules;
* compute is bf16-friendly (params fp32, matmuls cast by the Accelerator's
  dtype policy);
* optional ``remat`` per encoder layer (activation checkpointing — the
  reference delegates this to FSDP/Megatron flags, SURVEY §5).

Weight import from HF checkpoints is in
:mod:`accelerate_tpu.models.hub` (safetensors -> pytree, torch-free).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.fp8 import policy_dot_general as _pdg
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    num_labels: int = 2
    remat: bool = False

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        """4-layer test-size config for CI meshes."""
        kw.setdefault("vocab_size", 1024)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 4)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


# Megatron-style tensor-parallel layout: QKV/intermediate are column-split
# (output dim over ``tensor``), attn-out/FFN-down are row-split (input dim
# over ``tensor``), embeddings shard the vocab dim. The reference delegates
# TP entirely to transformers/Megatron (SURVEY §2.2 TP row); here the rules
# ship with the model.
BERT_SHARDING_RULES = [
    (r"embeddings/word_embeddings/embedding", P("tensor", None)),
    (r"attention/(query|key|value)/kernel", P(None, "tensor")),
    (r"attention/out/kernel", P("tensor", None)),
    (r"ffn/intermediate/kernel", P(None, "tensor")),
    (r"ffn/output/kernel", P("tensor", None)),
]


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dense = lambda name: nn.Dense(cfg.hidden_size, name=name, dtype=hidden.dtype, dot_general=_pdg())
        q = dense("query")(hidden)
        k = dense("key")(hidden)
        v = dense("value")(hidden)

        def split(x):
            return x.reshape(*x.shape[:-1], cfg.num_attention_heads, head_dim)

        q, k, v = split(q), split(k), split(v)
        from ..ops.attention import dot_product_attention

        mask = attention_mask[:, None, None, :]  # [B,1,1,S] additive-ready bool
        out = dot_product_attention(
            q,
            k,
            v,
            mask=mask,
            dropout_rate=0.0 if deterministic else cfg.attention_probs_dropout_prob,
            dropout_rng=None if deterministic else self.make_rng("dropout"),
        )
        out = out.reshape(*out.shape[:-2], cfg.hidden_size)
        out = nn.Dense(cfg.hidden_size, name="out", dtype=hidden.dtype, dot_general=_pdg())(out)
        if not deterministic:
            out = nn.Dropout(cfg.hidden_dropout_prob)(out, deterministic=False)
        return out


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, attention_mask, deterministic: bool = True):
        cfg = self.config
        attn_out = BertSelfAttention(cfg, name="attention")(hidden, attention_mask, deterministic)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="attention_norm", dtype=jnp.float32)(
            hidden + attn_out
        ).astype(hidden.dtype)

        ffn = nn.Dense(cfg.intermediate_size, name="ffn/intermediate", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        ffn = nn.gelu(ffn, approximate=False)
        ffn = nn.Dense(cfg.hidden_size, name="ffn/output", dtype=hidden.dtype, dot_general=_pdg())(ffn)
        if not deterministic:
            ffn = nn.Dropout(cfg.hidden_dropout_prob)(ffn, deterministic=False)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ffn_norm", dtype=jnp.float32)(
            hidden + ffn
        ).astype(hidden.dtype)
        return hidden


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, token_type_ids=None, deterministic: bool = True):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        positions = jnp.arange(input_ids.shape[-1])[None, :]
        emb = (
            nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embeddings/word_embeddings")(input_ids)
            + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, name="embeddings/position_embeddings")(positions)
            + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, name="embeddings/token_type_embeddings")(token_type_ids)
        )
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="embeddings/norm", dtype=jnp.float32)(emb).astype(
            emb.dtype
        )
        layer_cls = nn.remat(BertLayer, static_argnums=(3,)) if cfg.remat else BertLayer
        for i in range(cfg.num_hidden_layers):
            hidden = layer_cls(cfg, name=f"layer_{i}")(hidden, attention_mask, deterministic)
        return hidden


class BertForSequenceClassification(nn.Module):
    """Encoder + [CLS] pooler + classifier (the MRPC fine-tune head)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, token_type_ids=None, deterministic: bool = True):
        cfg = self.config
        hidden = BertEncoder(cfg, name="encoder")(input_ids, attention_mask, token_type_ids, deterministic)
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, name="pooler")(hidden[:, 0]))
        if not deterministic:
            pooled = nn.Dropout(cfg.hidden_dropout_prob)(pooled, deterministic=False)
        return nn.Dense(cfg.num_labels, name="classifier", dtype=jnp.float32)(pooled)


def create_bert_model(
    config: Optional[BertConfig] = None,
    seed: int = 0,
    seq_len: int = 128,
    batch_size: int = 2,
) -> Model:
    """Initialise a :class:`~accelerate_tpu.modeling.Model` wrapping
    BERT-for-classification with its TP sharding rules attached."""
    config = config or BertConfig.base()
    module = BertForSequenceClassification(config)
    dummy = {
        "input_ids": jnp.zeros((batch_size, seq_len), jnp.int32),
        "attention_mask": jnp.ones((batch_size, seq_len), jnp.bool_),
    }
    params = module.init(jax.random.key(seed), dummy["input_ids"], dummy["attention_mask"])["params"]

    def apply_fn(p, input_ids, attention_mask, token_type_ids=None, deterministic=True, rngs=None):
        if not deterministic and rngs is None:
            raise ValueError("deterministic=False (dropout on) requires rngs={'dropout': key}")
        return module.apply(
            {"params": p}, input_ids, attention_mask, token_type_ids, deterministic=deterministic, rngs=rngs
        )

    model = Model(apply_fn, params, sharding_rules=BERT_SHARDING_RULES, name="bert")
    model.config = config
    model.module = module
    return model


def bert_classification_loss(params, batch, apply_fn, rng=None):
    """Cross-entropy loss for the fine-tune head (fp32 logits/loss).
    Pass ``rng`` (e.g. from the Accelerator's per-step key) to train with
    dropout; without it the model runs deterministically."""
    logits = apply_fn(
        params,
        batch["input_ids"],
        batch["attention_mask"],
        batch.get("token_type_ids"),
        deterministic=rng is None,
        rngs=None if rng is None else {"dropout": rng},
    )
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    if "loss_mask" in batch:
        mask = batch["loss_mask"].astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()
