"""Whisper-style speech encoder-decoder (flax.linen): conv frontend over
log-mel features, sinusoidal encoder positions, learned decoder positions,
pre-LN transformer blocks, cached incremental decoding.

Extends the zoo beyond text (reference parity: the reference is
model-agnostic over torch modules — SURVEY §2.1's "works with any
nn.Module"; the TPU zoo demonstrates the same reach family by family).
Structure matches HF ``WhisperForConditionalGeneration`` so
``models/hub.py`` imports checkpoints element-for-element: conv1/conv2
(stride 2) + GELU, q/v/out projections biased and k unbiased, per-layer
pre-norms, tied decoder output embedding.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model


@dataclasses.dataclass
class WhisperConfig:
    vocab_size: int = 51865
    num_mel_bins: int = 80
    d_model: int = 384
    encoder_layers: int = 4
    decoder_layers: int = 4
    encoder_attention_heads: int = 6
    decoder_attention_heads: int = 6
    encoder_ffn_dim: int = 1536
    decoder_ffn_dim: int = 1536
    max_source_positions: int = 1500
    max_target_positions: int = 448
    layer_norm_eps: float = 1e-5
    max_decode_len: int = 128

    def __post_init__(self):
        if self.max_decode_len > self.max_target_positions:
            # positions past the table would silently clamp (JAX OOB gather)
            raise ValueError(
                f"max_decode_len ({self.max_decode_len}) exceeds max_target_positions "
                f"({self.max_target_positions})"
            )

    @classmethod
    def tiny(cls, **kw) -> "WhisperConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("num_mel_bins", 8)
        kw.setdefault("d_model", 32)
        kw.setdefault("encoder_layers", 2)
        kw.setdefault("decoder_layers", 2)
        kw.setdefault("encoder_attention_heads", 4)
        kw.setdefault("decoder_attention_heads", 4)
        kw.setdefault("encoder_ffn_dim", 64)
        kw.setdefault("decoder_ffn_dim", 64)
        kw.setdefault("max_source_positions", 32)
        kw.setdefault("max_target_positions", 32)
        kw.setdefault("max_decode_len", 32)
        return cls(**kw)


WHISPER_SHARDING_RULES = [
    (r"embed_tokens/embedding", P("tensor", None)),
    (r"(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"out_proj/kernel", P("tensor", None)),
    (r"fc1/kernel", P(None, "tensor")),
    (r"fc2/kernel", P("tensor", None)),
]


def sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed sinusoidal table: [length, channels] with sin | cos
    halves over log-spaced timescales."""
    if channels % 2 != 0:
        raise ValueError(f"channels must be even, got {channels}")
    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1).astype(np.float32)


class WhisperAttention(nn.Module):
    """MHA with HF Whisper's bias pattern (q/v/out biased, k unbiased) and
    the zoo's shared cache machinery for causal decode / cross K-V reuse."""

    d_model: int
    num_heads: int
    causal: bool = False
    max_decode_len: int = 448

    @nn.compact
    def __call__(self, hidden, kv=None, mask=None, decode=False, prime=True):
        cross = kv is not None
        kv_in = hidden if kv is None else kv
        head_dim = self.d_model // self.num_heads

        def split(x):
            return x.reshape(*x.shape[:-1], self.num_heads, head_dim)

        q = split(nn.Dense(self.d_model, name="q_proj", dtype=hidden.dtype)(hidden))
        if decode and cross and not self.causal:
            from ..ops.kv_cache import cached_cross_kv

            k, v = cached_cross_kv(
                self,
                kv_in,
                self.num_heads,
                head_dim,
                lambda: split(nn.Dense(self.d_model, use_bias=False, name="k_proj", dtype=kv_in.dtype)(kv_in)),
                lambda: split(nn.Dense(self.d_model, name="v_proj", dtype=kv_in.dtype)(kv_in)),
                prime,
            )
            k, v = k.astype(q.dtype), v.astype(q.dtype)
        else:
            k = split(nn.Dense(self.d_model, use_bias=False, name="k_proj", dtype=hidden.dtype)(kv_in))
            v = split(nn.Dense(self.d_model, name="v_proj", dtype=hidden.dtype)(kv_in))

        if decode and self.causal:
            from ..ops.kv_cache import cached_attention

            out = cached_attention(self, q, k, v, self.max_decode_len)
        else:
            from ..ops.attention import dot_product_attention

            out = dot_product_attention(
                q, k, v, mask=None if mask is None else mask[:, None, None, :], causal=self.causal
            )
        out = out.reshape(*out.shape[:-2], self.d_model)
        return nn.Dense(self.d_model, name="out_proj", dtype=hidden.dtype)(out)


class WhisperEncoderLayer(nn.Module):
    config: WhisperConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_self", dtype=hidden.dtype)(hidden)
        hidden = hidden + WhisperAttention(cfg.d_model, cfg.encoder_attention_heads, name="self_attn")(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_ffn", dtype=hidden.dtype)(hidden)
        h = nn.gelu(nn.Dense(cfg.encoder_ffn_dim, name="fc1", dtype=hidden.dtype)(h), approximate=False)
        return hidden + nn.Dense(cfg.d_model, name="fc2", dtype=hidden.dtype)(h)


class WhisperDecoderLayer(nn.Module):
    config: WhisperConfig

    @nn.compact
    def __call__(self, hidden, enc_out, decode=False, prime=True):
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_self", dtype=hidden.dtype)(hidden)
        hidden = hidden + WhisperAttention(
            cfg.d_model, cfg.decoder_attention_heads, causal=True,
            max_decode_len=cfg.max_decode_len, name="self_attn"
        )(h, decode=decode)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_cross", dtype=hidden.dtype)(hidden)
        hidden = hidden + WhisperAttention(
            cfg.d_model, cfg.decoder_attention_heads, name="cross_attn"
        )(h, kv=enc_out, decode=decode, prime=prime)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln_ffn", dtype=hidden.dtype)(hidden)
        h = nn.gelu(nn.Dense(cfg.decoder_ffn_dim, name="fc1", dtype=hidden.dtype)(h), approximate=False)
        return hidden + nn.Dense(cfg.d_model, name="fc2", dtype=hidden.dtype)(h)


class WhisperModel(nn.Module):
    config: WhisperConfig

    @nn.compact
    def __call__(self, input_features, decoder_input_ids, attention_mask=None, decode=False, encode=True):
        """``input_features`` [B, frames, num_mel_bins] (feature-last; HF's
        [B, mel, frames] transposed). ``decode=True`` runs the decoder
        incrementally; the encoder runs once at prefill."""
        cfg = self.config

        if not decode or encode:
            x = input_features
            x = nn.gelu(
                nn.Conv(cfg.d_model, (3,), padding=((1, 1),), name="conv1", dtype=x.dtype)(x),
                approximate=False,
            )
            x = nn.gelu(
                nn.Conv(cfg.d_model, (3,), strides=(2,), padding=((1, 1),), name="conv2", dtype=x.dtype)(x),
                approximate=False,
            )
            # fixed (NON-trainable) sinusoids, like HF's frozen
            # embed_positions: computed, not a param — fine-tuning must not
            # drift the table (checkpoints store exactly this formula)
            enc_pos = jnp.asarray(sinusoids(cfg.max_source_positions, cfg.d_model))
            x = x + enc_pos[None, : x.shape[1]].astype(x.dtype)
            for i in range(cfg.encoder_layers):
                x = WhisperEncoderLayer(cfg, name=f"enc_layer_{i}")(x)
            enc_out = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="enc_final_norm", dtype=x.dtype)(x)
        else:
            enc_out = None

        embed = nn.Embed(cfg.vocab_size, cfg.d_model, name="embed_tokens")
        if decode:
            b = decoder_input_ids.shape[0]
            s_enc = (input_features.shape[1] + 1) // 2  # conv2 stride halves frames
            store = self.variable("cache", "enc_out", jnp.zeros, (b, s_enc, cfg.d_model), jnp.float32)
            pos_idx = self.variable("cache", "dec_pos", lambda: jnp.zeros((), jnp.int32))
            if encode:
                store.value = enc_out.astype(jnp.float32)
            enc_out = store.value.astype(embed.embedding.dtype)
            positions = pos_idx.value + jnp.arange(decoder_input_ids.shape[1])
            pos_idx.value = pos_idx.value + decoder_input_ids.shape[1]
        else:
            positions = jnp.arange(decoder_input_ids.shape[1])

        dec_pos = self.param(
            "dec_pos/embedding",
            nn.initializers.normal(0.02),
            (cfg.max_target_positions, cfg.d_model),
        )
        d = embed(decoder_input_ids) + dec_pos[positions][None].astype(embed.embedding.dtype)
        for i in range(cfg.decoder_layers):
            d = WhisperDecoderLayer(cfg, name=f"dec_layer_{i}")(d, enc_out, decode, encode)
        d = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="dec_final_norm", dtype=d.dtype)(d)
        return d.astype(jnp.float32) @ embed.embedding.T.astype(jnp.float32)


def create_whisper_model(
    config: Optional[WhisperConfig] = None, seed: int = 0, n_frames: int = 16, dec_len: int = 8
) -> Model:
    config = config or WhisperConfig.tiny()
    module = WhisperModel(config)
    feats = jnp.zeros((2, n_frames, config.num_mel_bins), jnp.float32)
    ids = jnp.zeros((2, dec_len), jnp.int32)
    params = module.init(jax.random.key(seed), feats, ids)["params"]

    def apply_fn(p, input_features, decoder_input_ids, attention_mask=None, decode=False, cache=None):
        """decode=True threads the decoder KV cache (+ stored encoder
        output): pass ``cache`` (None primes it) -> ``(logits, new_cache)``."""
        if decode:
            variables = {"params": p}
            if cache is not None:
                variables["cache"] = cache
            logits, mutated = module.apply(
                variables,
                input_features,
                decoder_input_ids,
                decode=True,
                encode=cache is None,
                mutable=["cache"],
            )
            return logits, mutated["cache"]
        return module.apply({"params": p}, input_features, decoder_input_ids)

    model = Model(apply_fn, params, sharding_rules=WHISPER_SHARDING_RULES, name="whisper")
    model.config = config
    model.module = module
    return model
