"""Convolutional VAE (AutoencoderKL shape) for latent diffusion.

Completes the diffusion family: the reference's flagship diffusion
example drives a diffusers *latent*-diffusion pipeline
(reference: examples/inference/distributed/stable_diffusion.py — VAE +
text-conditioned UNet + CLIP text encoder); the VAE itself lives in the
diffusers package there. Here it is in-tree and TPU-shaped: NHWC convs,
GroupNorm statistics in fp32 (the UNet's stance), and the
encode/decode entry points are pure functions fit for ``jit``/``scan``.

* Encoder: conv_in → per-level ResBlocks with stride-2 downsample →
  mid block → 2·latent_channels head (mean, logvar).
* Decoder: mirror with nearest-neighbour upsample.
* ``scaling_factor`` follows the SD convention (latents are scaled to
  ~unit variance before the diffusion model sees them).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model
from .unet import ResBlock, _GroupNorm


@dataclasses.dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    sample_size: int = 32  # H = W of the image
    base_channels: int = 32
    channel_mults: Sequence[int] = (1, 2)  # len = number of levels; stride-2 between levels
    num_groups: int = 8
    scaling_factor: float = 0.18215  # SD latents convention
    kl_weight: float = 1e-4

    @property
    def downsample_factor(self) -> int:
        return 2 ** (len(self.channel_mults) - 1)

    @property
    def latent_size(self) -> int:
        return self.sample_size // self.downsample_factor

    @classmethod
    def tiny(cls, **kw) -> "VAEConfig":
        kw.setdefault("sample_size", 16)
        kw.setdefault("base_channels", 16)
        kw.setdefault("channel_mults", (1, 2))
        kw.setdefault("num_groups", 4)
        kw.setdefault("latent_channels", 2)
        return cls(**kw)


VAE_SHARDING_RULES = [
    # conv kernels [kh, kw, in, out]: column-split output channels over tensor
    (r"conv_(in|1|2)/kernel", P(None, None, None, "tensor")),
    (r"(latent_head|conv_out)/kernel", P(None, None, "tensor", None)),
]


class VAEEncoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # the VAE has no time conditioning; feed a zero embedding to reuse
        # the UNet ResBlock (its FiLM projection learns a plain bias)
        temb = jnp.zeros((x.shape[0], cfg.base_channels), x.dtype)
        h = nn.Conv(cfg.base_channels, (3, 3), padding="SAME", name="conv_in", dtype=x.dtype)(x)
        for lvl, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            h = ResBlock(ch, cfg.num_groups, name=f"down_{lvl}")(h, temb)
            if lvl != len(cfg.channel_mults) - 1:
                h = nn.Conv(ch, (3, 3), (2, 2), padding="SAME", name=f"downsample_{lvl}", dtype=h.dtype)(h)
        h = ResBlock(cfg.base_channels * cfg.channel_mults[-1], cfg.num_groups, name="mid")(h, temb)
        h = nn.silu(_GroupNorm(cfg.num_groups, name="norm_out")(h))
        # fp32 head: logvar exponentiation is precision-sensitive
        moments = nn.Conv(2 * cfg.latent_channels, (3, 3), padding="SAME", name="latent_head", dtype=jnp.float32)(h)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)


class VAEDecoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.config
        temb = jnp.zeros((z.shape[0], cfg.base_channels), z.dtype)
        ch = cfg.base_channels * cfg.channel_mults[-1]
        h = nn.Conv(ch, (3, 3), padding="SAME", name="conv_in", dtype=z.dtype)(z)
        h = ResBlock(ch, cfg.num_groups, name="mid")(h, temb)
        for lvl, mult in reversed(list(enumerate(cfg.channel_mults))):
            ch = cfg.base_channels * mult
            h = ResBlock(ch, cfg.num_groups, name=f"up_{lvl}")(h, temb)
            if lvl != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = nn.Conv(c, (3, 3), padding="SAME", name=f"upsample_{lvl}", dtype=h.dtype)(h)
        h = nn.silu(_GroupNorm(cfg.num_groups, name="norm_out")(h))
        return nn.Conv(cfg.in_channels, (3, 3), padding="SAME", name="conv_out", dtype=jnp.float32)(h)


class VAE(nn.Module):
    """Reconstruction path (what ``init`` traces; encode/decode are
    exposed as separate apply methods on the created Model)."""

    config: VAEConfig

    def setup(self):
        self.encoder = VAEEncoder(self.config)
        self.decoder = VAEDecoder(self.config)

    def __call__(self, x, rng=None):
        mean, logvar = self.encoder(x)
        z = mean if rng is None else mean + jnp.exp(0.5 * logvar) * jax.random.normal(rng, mean.shape)
        return self.decoder(z.astype(x.dtype)), mean, logvar

    def encode(self, x, rng=None):
        """Image [B,H,W,C] → scaled latents [B,h,w,latent] (+ moments)."""
        mean, logvar = self.encoder(x)
        z = mean if rng is None else mean + jnp.exp(0.5 * logvar) * jax.random.normal(rng, mean.shape)
        return z * self.config.scaling_factor, mean, logvar

    def decode(self, z):
        """Scaled latents → image."""
        return self.decoder(z / self.config.scaling_factor)


def vae_loss(params, batch, apply_fn, rng, kl_weight: Optional[float] = None, config: Optional[VAEConfig] = None):
    """ELBO: MSE reconstruction + KL(q(z|x) ‖ N(0,1)) (fp32, per-element
    means so the weight is resolution-independent)."""
    recon, mean, logvar = apply_fn(params, batch["pixel_values"], rng)
    x = batch["pixel_values"].astype(jnp.float32)
    rec = jnp.mean((recon - x) ** 2)
    kl = 0.5 * jnp.mean(jnp.exp(logvar) + mean**2 - 1.0 - logvar)
    weight = kl_weight if kl_weight is not None else (config.kl_weight if config else 1e-4)
    return rec + weight * kl


def create_vae_model(config: Optional[VAEConfig] = None, seed: int = 0, batch_size: int = 2) -> Model:
    config = config or VAEConfig.tiny()
    module = VAE(config)
    x = jnp.zeros((batch_size, config.sample_size, config.sample_size, config.in_channels), jnp.float32)
    params = module.init(jax.random.key(seed), x)["params"]

    def _cast(p, x):
        leaf = jax.tree_util.tree_leaves(p)[0]
        return x.astype(leaf.dtype) if jnp.issubdtype(leaf.dtype, jnp.floating) else x

    def apply_fn(p, pixel_values, rng=None):
        return module.apply({"params": p}, _cast(p, pixel_values), rng)

    model = Model(apply_fn, params, sharding_rules=VAE_SHARDING_RULES, name="vae")
    model.config = config
    model.module = module
    model.encode_fn = lambda p, x, rng=None: module.apply({"params": p}, _cast(p, x), rng, method=VAE.encode)
    model.decode_fn = lambda p, z: module.apply({"params": p}, z, method=VAE.decode)
    return model
