"""Qwen3-MoE: Qwen3 attention + top-k routed experts on the Mixtral core.

HF's own comment on the routing ("the only diff with the mixtral sparse
moe block") is the spec: Qwen3-MoE is the Mixtral architecture with

* Qwen3's attention (per-head q/k RMSNorm, explicit ``head_dim``, no
  qkv biases) — ``MixtralConfig.qk_norm``/``head_dim`` knobs;
* a separate expert FF width (``moe_intermediate_size``, 768 vs the
  dense 6144);
* combine weights that are renormalised over the selected experts only
  when ``norm_topk_prob`` is set (true on the released 30B-A3B/235B
  checkpoints — ``MixtralConfig.norm_topk``);
* many small experts (128, top-8) instead of Mixtral's 8, top-2.

Like :mod:`.mixtral`, this family is the expert-axis training surface
(forward/training; the decode contract lives with the dense families).
Parity vs ``transformers.Qwen3MoeForCausalLM`` in tests/test_hf_parity.py.
The reference has no MoE model support at all (SURVEY §2.2 EP row).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .mixtral import (
    MIXTRAL_SHARDING_RULES,
    MixtralConfig,
    MixtralModel,
    create_mixtral_model,
    mixtral_lm_loss,
)

QWEN3_MOE_SHARDING_RULES = MIXTRAL_SHARDING_RULES
Qwen3MoeModel = MixtralModel
qwen3_moe_lm_loss = mixtral_lm_loss


@dataclasses.dataclass
class Qwen3MoeConfig(MixtralConfig):
    """Mixtral config with Qwen3-30B-A3B-class defaults (128 experts,
    top-8, qk-norm, 768-wide experts)."""

    vocab_size: int = 151936
    hidden_size: int = 2048
    intermediate_size: int = 6144
    num_hidden_layers: int = 48
    num_attention_heads: int = 32
    num_key_value_heads: int = 4
    head_dim: Optional[int] = 128
    num_local_experts: int = 128
    num_experts_per_tok: int = 8
    moe_intermediate_size: Optional[int] = 768
    max_position_embeddings: int = 40960
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    router_aux_loss_coef: float = 0.001  # transformers Qwen3MoeConfig default
    qk_norm: bool = True
    norm_topk: bool = True  # released checkpoints set norm_topk_prob

    @classmethod
    def tiny(cls, **kw) -> "Qwen3MoeConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 96)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("num_local_experts", 4)
        kw.setdefault("num_experts_per_tok", 2)
        kw.setdefault("moe_intermediate_size", 48)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    @classmethod
    def qwen3_30b_a3b(cls, **kw) -> "Qwen3MoeConfig":
        return cls(**kw)


def create_qwen3_moe_model(config: Optional[Qwen3MoeConfig] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the mixtral module
    with Qwen3's attention and routing conventions."""
    return create_mixtral_model(config or Qwen3MoeConfig.tiny(), seed=seed, seq_len=seq_len)
