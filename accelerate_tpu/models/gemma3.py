"""Gemma3 (text): the gemma lineage's third generation on the llama core.

Relative to Gemma2, Gemma3 keeps the sandwich norms, the
``query_pre_attn_scalar`` scale, scaled embeddings, and the tied head —
and changes:

* per-head **q/k RMSNorm** (``qk_norm`` — zero-centred ``(1+scale)``
  like every Gemma norm) instead of attention logit softcapping, which
  is GONE (``attn_logit_softcap=None``, final softcap too);
* a **5:1 local/global pattern** (``layer_types``: five
  ``sliding_attention`` layers per ``full_attention`` layer) with a
  1024/4096-token window;
* **dual rope bases** (``rope_local_theta``): sliding layers rotate with
  theta 10k and no scaling, full layers with theta 1M (+``rope_scaling``
  linear factor 8 on the 4B+ checkpoints).

Per-layer attention kinds need ``scan_layers=False`` (one scanned block
shares a static config), so Gemma3 defaults to the unrolled stack.
Parity vs ``transformers.Gemma3ForCausalLM`` in tests/test_hf_parity.py.
The reference has no in-tree models (SURVEY §2.2); this family is zoo
surface.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

GEMMA3_SHARDING_RULES = LLAMA_SHARDING_RULES
Gemma3Model = LlamaModel


def _five_to_one(n_layers: int) -> tuple:
    """HF Gemma3 pattern: every 6th layer is global, the rest slide."""
    return tuple(
        "full_attention" if (i + 1) % 6 == 0 else "sliding_attention" for i in range(n_layers)
    )


@dataclasses.dataclass
class Gemma3Config(LlamaConfig):
    """Llama config with google/gemma-3-1b text defaults (5:1 local/global,
    dual rope bases, per-head qk-norm, MQA, 512-token window)."""

    vocab_size: int = 262144
    hidden_size: int = 1152
    intermediate_size: int = 6912
    num_hidden_layers: int = 26
    num_attention_heads: int = 4
    num_key_value_heads: int = 1
    head_dim: Optional[int] = 256
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-6
    mlp_activation: str = "gelu_tanh"
    norm_plus_one: bool = True
    scale_embeddings: bool = True
    tie_word_embeddings: bool = True
    sandwich_norm: bool = True
    qk_norm: bool = True
    query_pre_attn_scalar: Optional[float] = 256.0
    sliding_window: Optional[int] = 512
    rope_theta: float = 1_000_000.0
    rope_local_theta: Optional[float] = 10_000.0
    layer_types: Optional[tuple] = None  # filled per num_hidden_layers below
    scan_layers: bool = False  # per-layer attention kinds need the unrolled stack

    def __post_init__(self):
        if self.layer_types is None:
            self.layer_types = _five_to_one(self.num_hidden_layers)
        if len(self.layer_types) != self.num_hidden_layers:
            raise ValueError(
                f"layer_types has {len(self.layer_types)} entries for "
                f"{self.num_hidden_layers} layers — pass both together (or neither)"
            )

    @classmethod
    def tiny(cls, **kw) -> "Gemma3Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("head_dim", 16)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("sliding_window", 8)  # small enough for the band to bite
        kw.setdefault("query_pre_attn_scalar", 32.0)  # != head_dim: load-bearing
        kw.setdefault("layer_types", ("sliding_attention", "full_attention"))
        return cls(**kw)

    @classmethod
    def gemma3_1b(cls, **kw) -> "Gemma3Config":
        return cls(**kw)


def create_gemma3_model(config: Optional[Gemma3Config] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with Gemma3's dual rope bases, qk-norms, and 5:1 attention pattern."""
    return create_llama_model(config or Gemma3Config.tiny(), seed=seed, seq_len=seq_len)
