"""T5 encoder-decoder (flax.linen): relative position bias, RMS-style
LayerNorm, ReLU/GeGLU FFN, cross-attention.

Fourth model family of the reference's Megatron parser set (reference:
src/accelerate/utils/dataclasses.py:2532-2662 — bert/gpt2/t5/llama). Same
mesh conventions as the rest of the zoo; the encoder-decoder structure also
exercises cross-attention sharding (kv from a different sequence).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model
from ..ops.fp8 import policy_dot_general as _pdg
from .llama import RMSNorm  # T5's LayerNorm is RMS (no mean subtraction)


@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32128
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_layers: int = 6  # per stack (encoder and decoder)
    num_attention_heads: int = 8
    head_dim: int = 64
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    remat: bool = False
    # decoder KV-cache length for incremental generation
    max_decode_len: int = 128

    @classmethod
    def small(cls, **kw) -> "T5Config":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "T5Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("head_dim", 16)
        return cls(**kw)


T5_SHARDING_RULES = [
    (r"shared/embedding", P("tensor", None)),
    (r"(q|k|v)_proj/kernel", P(None, "tensor")),
    (r"o_proj/kernel", P("tensor", None)),
    (r"ffn/wi(_\d)?/kernel", P(None, "tensor")),
    (r"ffn/wo/kernel", P("tensor", None)),
    (r"lm_head/kernel", P(None, "tensor")),
]


def _bucketize(rel: jax.Array, num_buckets: int, max_distance: int, bidirectional: bool) -> jax.Array:
    """T5's log-binned bucketing of a relative-position array ``rel =
    mem_pos - ctx_pos`` — the ONE copy of the formula, shared by the
    teacher-forced path and the absolute-position cached-decode path."""
    buckets = 0
    if bidirectional:
        num_buckets //= 2
        buckets = jnp.where(rel > 0, num_buckets, 0)
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    log_bucket = max_exact + (
        jnp.log(jnp.maximum(rel, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    log_bucket = jnp.minimum(log_bucket, num_buckets - 1)
    return buckets + jnp.where(is_small, rel, log_bucket)


def relative_position_buckets(
    q_len: int, k_len: int, num_buckets: int, max_distance: int, bidirectional: bool
) -> jax.Array:
    """T5's log-binned relative position -> bucket id [q_len, k_len]."""
    rel = jnp.arange(k_len)[None, :] - jnp.arange(q_len)[:, None]
    return _bucketize(rel, num_buckets, max_distance, bidirectional)


class T5Attention(nn.Module):
    config: T5Config
    causal: bool = False
    has_relative_bias: bool = False

    def _bias_table(self):
        return self.param(
            "relative_bias/embedding",
            nn.initializers.normal(1.0),
            (self.config.relative_attention_num_buckets, self.config.num_attention_heads),
        )

    @nn.compact
    def __call__(self, hidden, kv=None, mask=None, position_bias=None, decode=False, prime=True):
        """Returns ``(out, position_bias)``. Like HF ``T5Stack``, the bias
        table lives only in the layer-0 attention (``has_relative_bias``);
        every later layer receives the computed ``position_bias`` and adds
        the same [1, H, Q, K] bias to its logits.

        ``decode=True`` on the causal self-attention switches to a fixed
        [B, max_decode_len] KV cache updated with dynamic_update_slice —
        prefill (full prefix) and per-token steps share the path. On
        CROSS-attention, decode mode projects the encoder output to K/V
        once at prefill (``prime=True``) and reuses the cached projections
        on every step (HF caches cross-attn K/V the same way)."""
        cfg = self.config
        cross = kv is not None
        kv = hidden if kv is None else kv
        inner = cfg.num_attention_heads * cfg.head_dim
        q = nn.Dense(inner, use_bias=False, name="q_proj", dtype=hidden.dtype, dot_general=_pdg())(hidden)

        def split(x):
            return x.reshape(*x.shape[:-1], cfg.num_attention_heads, cfg.head_dim)

        q = split(q)
        if decode and cross and not self.causal:
            from ..ops.kv_cache import cached_cross_kv

            k, v = cached_cross_kv(
                self,
                kv,
                cfg.num_attention_heads,
                cfg.head_dim,
                lambda: split(nn.Dense(inner, use_bias=False, name="k_proj", dtype=kv.dtype, dot_general=_pdg())(kv)),
                lambda: split(nn.Dense(inner, use_bias=False, name="v_proj", dtype=kv.dtype, dot_general=_pdg())(kv)),
                prime,
            )
            k, v = k.astype(q.dtype), v.astype(q.dtype)
        else:
            k = split(nn.Dense(inner, use_bias=False, name="k_proj", dtype=hidden.dtype, dot_general=_pdg())(kv))
            v = split(nn.Dense(inner, use_bias=False, name="v_proj", dtype=hidden.dtype, dot_general=_pdg())(kv))

        if decode and self.causal:
            out, position_bias = self._cached_causal(q, k, v, position_bias)
        else:
            # T5 does NOT scale by sqrt(d); fold relative bias into logits
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
            if position_bias is None and self.has_relative_bias:
                buckets = relative_position_buckets(
                    q.shape[1],
                    k.shape[1],
                    cfg.relative_attention_num_buckets,
                    cfg.relative_attention_max_distance,
                    bidirectional=not self.causal,
                )
                position_bias = self._bias_table()[buckets].transpose(2, 0, 1)[None].astype(jnp.float32)
            if position_bias is not None:
                logits = logits + position_bias
            if self.causal:
                cmask = jnp.arange(q.shape[1])[:, None] >= jnp.arange(k.shape[1])[None, :]
                logits = jnp.where(cmask[None, None], logits, jnp.finfo(jnp.float32).min)
            if mask is not None:
                logits = jnp.where(mask[:, None, None, :], logits, jnp.finfo(jnp.float32).min)
            weights = jax.nn.softmax(logits, axis=-1).astype(hidden.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        out = out.reshape(*out.shape[:-2], inner)
        out = nn.Dense(cfg.hidden_size, use_bias=False, name="o_proj", dtype=hidden.dtype, dot_general=_pdg())(out)
        return out, position_bias

    def _cached_causal(self, q, k, v, position_bias):
        """Incremental self-attention over the shared fixed-size cache
        (ops/kv_cache.py); T5 specifics enter as ``scale=1.0`` (no sqrt(d))
        and a relative-bias callback over ABSOLUTE positions."""
        from ..ops.kv_cache import cached_attention

        cfg = self.config
        computed = {"bias": position_bias}

        def bias_fn(q_pos, key_pos):
            if computed["bias"] is None and self.has_relative_bias:
                buckets = _bucketize(
                    key_pos[None, :] - q_pos[:, None],
                    cfg.relative_attention_num_buckets,
                    cfg.relative_attention_max_distance,
                    bidirectional=False,
                )
                computed["bias"] = (
                    self._bias_table()[buckets].transpose(2, 0, 1)[None].astype(jnp.float32)
                )
            return computed["bias"]

        out = cached_attention(self, q, k, v, cfg.max_decode_len, scale=1.0, bias_fn=bias_fn)
        return out, computed["bias"]


class T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        h = nn.Dense(cfg.intermediate_size, use_bias=False, name="wi", dtype=hidden.dtype, dot_general=_pdg())(hidden)
        h = nn.relu(h)
        return nn.Dense(cfg.hidden_size, use_bias=False, name="wo", dtype=hidden.dtype, dot_general=_pdg())(h)


class T5EncoderLayer(nn.Module):
    config: T5Config
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, hidden, mask, position_bias=None):
        cfg = self.config
        attn_out, position_bias = T5Attention(
            cfg, causal=False, has_relative_bias=self.has_relative_bias, name="attn"
        )(RMSNorm(cfg.layer_norm_eps, name="ln_attn")(hidden), mask=mask, position_bias=position_bias)
        hidden = hidden + attn_out
        hidden = hidden + T5FFN(cfg, name="ffn")(RMSNorm(cfg.layer_norm_eps, name="ln_ffn")(hidden))
        return hidden, position_bias


class T5DecoderLayer(nn.Module):
    config: T5Config
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, hidden, enc_out, enc_mask, position_bias=None, decode=False, prime=True):
        cfg = self.config
        self_out, position_bias = T5Attention(
            cfg, causal=True, has_relative_bias=self.has_relative_bias, name="self_attn"
        )(RMSNorm(cfg.layer_norm_eps, name="ln_self")(hidden), position_bias=position_bias, decode=decode)
        hidden = hidden + self_out
        # HF T5 cross-attention carries no position bias (zeros)
        cross_out, _ = T5Attention(cfg, causal=False, name="cross_attn")(
            RMSNorm(cfg.layer_norm_eps, name="ln_cross")(hidden),
            kv=enc_out,
            mask=enc_mask,
            decode=decode,
            prime=prime,
        )
        hidden = hidden + cross_out
        hidden = hidden + T5FFN(cfg, name="ffn")(RMSNorm(cfg.layer_norm_eps, name="ln_ffn")(hidden))
        return hidden, position_bias


class T5Model(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, input_ids, decoder_input_ids, attention_mask=None, decode=False, encode=True):
        """``decode=True`` runs the decoder incrementally against its KV
        cache. The encoder runs once at prefill (``encode=True``) and its
        output + mask persist in the cache collection; later steps pass
        ``encode=False`` and skip the encoder stack entirely."""
        cfg = self.config
        shared = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="shared")
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids, jnp.bool_)

        from ..parallel.sharding import maybe_shard

        spec = P(("data", "fsdp"), "seq", None)
        enc_layer = nn.remat(T5EncoderLayer, prevent_cse=False) if cfg.remat else T5EncoderLayer
        dec_layer = (
            nn.remat(T5DecoderLayer, prevent_cse=False, static_argnums=(5, 6)) if cfg.remat else T5DecoderLayer
        )

        if not decode or encode:
            h = maybe_shard(shared(input_ids), spec)
            enc_bias = None  # computed by layer 0, shared by layers 1..N (HF T5Stack)
            for i in range(cfg.num_layers):
                h, enc_bias = enc_layer(cfg, has_relative_bias=(i == 0), name=f"enc_layer_{i}")(
                    h, attention_mask, enc_bias
                )
            enc_out = RMSNorm(cfg.layer_norm_eps, name="enc_final_norm")(h)
        else:
            enc_out = None

        if decode:
            # persist encoder activations + mask for the per-token steps
            b = decoder_input_ids.shape[0]
            s_enc = input_ids.shape[1]
            enc_store = self.variable(
                "cache", "enc_out", jnp.zeros, (b, s_enc, cfg.hidden_size), jnp.float32
            )
            mask_store = self.variable("cache", "enc_mask", jnp.zeros, (b, s_enc), jnp.bool_)
            if encode:
                enc_store.value = enc_out.astype(jnp.float32)
                mask_store.value = attention_mask
            enc_out = enc_store.value.astype(shared.embedding.dtype)
            attention_mask = mask_store.value

        d = maybe_shard(shared(decoder_input_ids), spec)
        dec_bias = None
        for i in range(cfg.num_layers):
            d, dec_bias = dec_layer(cfg, has_relative_bias=(i == 0), name=f"dec_layer_{i}")(
                d, enc_out, attention_mask, dec_bias, decode, encode
            )
        d = RMSNorm(cfg.layer_norm_eps, name="dec_final_norm")(d)
        if cfg.tie_word_embeddings:
            # T5 scales tied-logits by 1/sqrt(d) (HF modeling_t5 parity)
            d = d * (cfg.hidden_size**-0.5)
            return d.astype(jnp.float32) @ shared.embedding.T.astype(jnp.float32)
        return nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head", dtype=jnp.float32)(d)


def create_t5_model(config: Optional[T5Config] = None, seed: int = 0, seq_len: int = 32) -> Model:
    config = config or T5Config.tiny()
    module = T5Model(config)
    dummy = jnp.zeros((2, seq_len), jnp.int32)
    params = module.init(jax.random.key(seed), dummy, dummy)["params"]

    def apply_fn(p, input_ids, decoder_input_ids, attention_mask=None, decode=False, cache=None):
        """decode=True threads the decoder KV cache (+ stored encoder
        output): pass ``cache`` (None primes it — the encoder runs once)
        and receive ``(logits, new_cache)``."""
        if decode:
            variables = {"params": p}
            if cache is not None:
                variables["cache"] = cache
            logits, mutated = module.apply(
                variables,
                input_ids,
                decoder_input_ids,
                attention_mask,
                decode=True,
                encode=cache is None,
                mutable=["cache"],
            )
            return logits, mutated["cache"]
        return module.apply({"params": p}, input_ids, decoder_input_ids, attention_mask)

    model = Model(apply_fn, params, sharding_rules=T5_SHARDING_RULES, name="t5")
    model.config = config
    model.module = module
    return model


def seq2seq_lm_loss(params, batch, apply_fn):
    """Teacher-forced seq2seq cross entropy. ``decoder_input_ids`` are the
    labels shifted right (pad-start); positions with label==-100 or where
    ``decoder_loss_mask`` is 0 are excluded."""
    labels = batch["labels"]
    dec_in = batch.get("decoder_input_ids")
    if dec_in is None:
        dec_in = jnp.pad(labels[:, :-1], ((0, 0), (1, 0)))
        dec_in = jnp.where(dec_in == -100, 0, dec_in)
    logits = apply_fn(params, batch["input_ids"], dec_in, batch.get("attention_mask"))
    mask = (labels != -100).astype(jnp.float32)
    if "decoder_loss_mask" in batch:
        mask = mask * batch["decoder_loss_mask"].astype(jnp.float32)
    safe_labels = jnp.where(labels == -100, 0, labels)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
