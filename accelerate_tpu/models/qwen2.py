"""Qwen2: the llama architecture + biased q/k/v projections.

Qwen2/Qwen2.5 decoders are structurally llama (RMSNorm pre-norm,
rotary, GQA, SwiGLU) with bias vectors on the q/k/v projections only
(``LlamaConfig.qkv_bias``) and their own widths/theta; small variants
tie the LM head to the embeddings (the importer's existing fallback).
Sliding-window attention exists in the family but ships disabled
(``use_sliding_window=False``) — pass ``sliding_window=`` explicitly to
enable the band, which then rides the same dense/banded-flash/paged
paths as Mistral.

Like :mod:`.mistral`, the module/sharding/loss surfaces are the llama
ones; only the config and the checkpoint importer differ. The reference
has no in-tree models (SURVEY §2.2); importer parity is tested against
``transformers.Qwen2ForCausalLM`` in tests/test_hf_parity.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

QWEN2_SHARDING_RULES = LLAMA_SHARDING_RULES
Qwen2Model = LlamaModel


@dataclasses.dataclass
class Qwen2Config(LlamaConfig):
    """Llama config with Qwen2-7B defaults (qkv bias on, window off)."""

    vocab_size: int = 152064
    hidden_size: int = 3584
    intermediate_size: int = 18944
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    qkv_bias: bool = True

    @classmethod
    def tiny(cls, **kw) -> "Qwen2Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    @classmethod
    def qwen2_7b(cls, **kw) -> "Qwen2Config":
        return cls(**kw)


def create_qwen2_model(config: Optional[Qwen2Config] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with Qwen2's biased q/k/v projections."""
    return create_llama_model(config or Qwen2Config.tiny(), seed=seed, seq_len=seq_len)
