from .bert import (
    BERT_SHARDING_RULES,
    BertConfig,
    BertForSequenceClassification,
    bert_classification_loss,
    create_bert_model,
)
from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    causal_lm_loss,
    create_llama_model,
)
