"""Model zoo: TPU-first flax implementations with mesh sharding rules
(bert/gpt2/gptneox/t5/llama/mistral/qwen2/qwen3/olmo2/gemma/gemma2/gemma3/phi3/mixtral/qwen3moe/resnet/vit/whisper/clip/unet/vae)
+ HF safetensors weight import. The reference delegates models to
transformers; here they ship in-tree (SURVEY hard-part #3: torch-free
model story)."""

from .bert import (
    BERT_SHARDING_RULES,
    BertConfig,
    BertForSequenceClassification,
    bert_classification_loss,
    create_bert_model,
)
from .gptneox import (
    GPTNEOX_SHARDING_RULES,
    GPTNeoXConfig,
    GPTNeoXModel,
    create_gptneox_model,
)
from .gpt2 import (
    GPT2_SHARDING_RULES,
    GPT2Config,
    GPT2Model,
    create_gpt2_model,
)
from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    causal_lm_loss,
    create_llama_model,
)
from .mistral import (
    MISTRAL_SHARDING_RULES,
    MistralConfig,
    MistralModel,
    create_mistral_model,
)
from .gemma import (
    GEMMA_SHARDING_RULES,
    GemmaConfig,
    GemmaModel,
    create_gemma_model,
)
from .phi3 import (
    PHI3_SHARDING_RULES,
    Phi3Config,
    Phi3Model,
    create_phi3_model,
)
from .qwen2 import (
    QWEN2_SHARDING_RULES,
    Qwen2Config,
    Qwen2Model,
    create_qwen2_model,
)
from .qwen3 import (
    QWEN3_SHARDING_RULES,
    Qwen3Config,
    Qwen3Model,
    create_qwen3_model,
)
from .olmo2 import (
    OLMO2_SHARDING_RULES,
    Olmo2Config,
    Olmo2Model,
    create_olmo2_model,
)
from .gemma2 import (
    GEMMA2_SHARDING_RULES,
    Gemma2Config,
    Gemma2Model,
    create_gemma2_model,
)
from .gemma3 import (
    GEMMA3_SHARDING_RULES,
    Gemma3Config,
    Gemma3Model,
    create_gemma3_model,
)
from .mixtral import (
    MIXTRAL_SHARDING_RULES,
    MixtralConfig,
    MixtralModel,
    create_mixtral_model,
    mixtral_lm_loss,
)
from .qwen3_moe import (
    QWEN3_MOE_SHARDING_RULES,
    Qwen3MoeConfig,
    Qwen3MoeModel,
    create_qwen3_moe_model,
    qwen3_moe_lm_loss,
)
from .resnet import (
    RESNET_SHARDING_RULES,
    ResNet,
    ResNetConfig,
    create_resnet_model,
    resnet_classification_loss,
)
from .t5 import (
    T5_SHARDING_RULES,
    T5Config,
    T5Model,
    create_t5_model,
    seq2seq_lm_loss,
)
from .vit import (
    VIT_SHARDING_RULES,
    ViT,
    ViTConfig,
    create_vit_model,
    vit_classification_loss,
)
from .clip import (
    CLIP_SHARDING_RULES,
    CLIPConfig,
    CLIPModel,
    clip_contrastive_loss,
    create_clip_model,
)
from .whisper import (
    WHISPER_SHARDING_RULES,
    WhisperConfig,
    WhisperModel,
    create_whisper_model,
)
from .unet import (
    UNET_SHARDING_RULES,
    UNet2D,
    UNetConfig,
    create_unet_model,
)
from .vae import (
    VAE_SHARDING_RULES,
    VAE,
    VAEConfig,
    create_vae_model,
    vae_loss,
)
from .hub import (  # noqa: E402 — HF safetensors importers
    load_hf_bert,
    load_hf_gemma,
    load_hf_gemma2,
    load_hf_gemma3,
    load_hf_gpt2,
    load_hf_gptneox,
    load_hf_llama,
    load_hf_mistral,
    load_hf_mixtral,
    load_hf_phi3,
    load_hf_olmo2,
    load_hf_qwen2,
    load_hf_qwen3,
    load_hf_qwen3_moe,
    load_hf_t5,
    load_hf_vit,
    load_hf_clip,
    load_hf_whisper,
    read_safetensors_state,
)
