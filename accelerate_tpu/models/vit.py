"""Vision Transformer (flax.linen) — the transformer CV model.

Reference analogue: the reference's CV path delegates to timm
(examples/cv_example.py `create_model`); the in-tree zoo needs a
transformer vision model next to ResNet. TPU-first choices:

* patchify as a strided conv — one big matmul-shaped op for the MXU
  (kernel = patch, stride = patch), NHWC;
* pre-LN encoder blocks sharing the BERT Megatron TP layout (QKV/up
  column-split, out/down row-split over ``tensor``);
* no BatchNorm — LayerNorm only, so the model is stateless (no
  ``has_state`` plumbing needed) and shards trivially;
* optional ``remat`` per block.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modeling import Model
from ..ops.fp8 import policy_dot_general as _pdg


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    num_classes: int = 1000
    dropout_rate: float = 0.0
    layer_norm_eps: float = 1e-6
    remat: bool = False

    @classmethod
    def base(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


VIT_SHARDING_RULES = [
    (r"attention/(query|key|value)/kernel", P(None, "tensor")),
    (r"attention/out/kernel", P("tensor", None)),
    (r"mlp/up/kernel", P(None, "tensor")),
    (r"mlp/down/kernel", P("tensor", None)),
    (r"head/kernel", P(None, "tensor")),
]


class ViTBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        norm = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, name=name, dtype=jnp.float32)

        x = norm("norm1")(hidden).astype(hidden.dtype)
        dense = lambda name: nn.Dense(cfg.hidden_size, name=name, dtype=hidden.dtype, dot_general=_pdg())
        q = dense("attention/query")(x)
        k = dense("attention/key")(x)
        v = dense("attention/value")(x)

        def split(t):
            return t.reshape(*t.shape[:-1], cfg.num_attention_heads, head_dim)

        from ..ops.attention import dot_product_attention

        out = dot_product_attention(split(q), split(k), split(v))
        out = out.reshape(*out.shape[:-2], cfg.hidden_size)
        out = dense("attention/out")(out)
        if not deterministic and cfg.dropout_rate:
            out = nn.Dropout(cfg.dropout_rate)(out, deterministic=False)
        hidden = hidden + out

        x = norm("norm2")(hidden).astype(hidden.dtype)
        x = nn.Dense(cfg.intermediate_size, name="mlp/up", dtype=hidden.dtype, dot_general=_pdg())(x)
        x = nn.gelu(x)
        x = nn.Dense(cfg.hidden_size, name="mlp/down", dtype=hidden.dtype, dot_general=_pdg())(x)
        if not deterministic and cfg.dropout_rate:
            x = nn.Dropout(cfg.dropout_rate)(x, deterministic=False)
        return hidden + x


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        """images: [B, H, W, 3] NHWC float. Returns [B, num_classes] fp32."""
        cfg = self.config
        p = cfg.patch_size
        x = nn.Conv(
            cfg.hidden_size, (p, p), strides=(p, p), padding="VALID", dtype=images.dtype, name="patch_embed"
        )(images)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)

        cls = self.param("cls_token", nn.initializers.zeros_init(), (1, 1, cfg.hidden_size))
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(x.dtype), (b, 1, c)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, h * w + 1, cfg.hidden_size)
        )
        x = x + pos.astype(x.dtype)

        block_cls = nn.remat(ViTBlock) if cfg.remat else ViTBlock
        for i in range(cfg.num_hidden_layers):
            x = block_cls(cfg, name=f"block_{i}")(x, deterministic)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="final_norm", dtype=jnp.float32)(x)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


def create_vit_model(
    config: Optional[ViTConfig] = None,
    seed: int = 0,
    batch_size: int = 2,
) -> Model:
    """Initialise a :class:`~accelerate_tpu.modeling.Model` wrapping ViT."""
    config = config or ViTConfig.base()
    module = ViT(config)
    dummy = jnp.zeros((batch_size, config.image_size, config.image_size, 3), jnp.float32)
    params = module.init(jax.random.key(seed), dummy)["params"]

    def apply_fn(p, images, deterministic=True, rngs=None):
        # follow the casted params' dtype (see resnet.py: fp32 inputs would
        # otherwise upcast every layer back to fp32)
        leaf = jax.tree_util.tree_leaves(p)[0]
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            images = images.astype(leaf.dtype)
        return module.apply({"params": p}, images, deterministic=deterministic, rngs=rngs)

    model = Model(apply_fn, params, sharding_rules=VIT_SHARDING_RULES, name="vit")
    model.config = config
    model.module = module
    return model


def vit_classification_loss(params, batch, apply_fn=None):
    """Cross-entropy on ``{"images", "labels"}`` (fp32 logits/loss)."""
    logits = apply_fn(params, batch["images"])
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return nll.mean()
