"""OLMo2: the llama architecture with post-norms and flat q/k RMSNorm.

OLMo2 decoders reorder normalization relative to llama: each sublayer's
OUTPUT is normalized before the residual add (``LlamaConfig.norm_after``
— ``post_attn_norm``/``post_ffn_norm``, no input norms), and RMSNorm is
applied to the FLAT q/k projections before the head split
(``qk_norm_flat`` — ``[H*head_dim]``/``[H_kv*head_dim]`` scales, a
different statistic than Qwen3's per-head norm). Rope theta is 500000;
widths are llama-7B-class.

Like the other llama variants, the module/sharding/loss surfaces are the
llama ones; only the config knobs and the checkpoint importer differ.
The reference has no in-tree models (SURVEY §2.2); importer parity is
tested against ``transformers.Olmo2ForCausalLM`` in
tests/test_hf_parity.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import (
    LLAMA_SHARDING_RULES,
    LlamaConfig,
    LlamaModel,
    create_llama_model,
)

OLMO2_SHARDING_RULES = LLAMA_SHARDING_RULES
Olmo2Model = LlamaModel


@dataclasses.dataclass
class Olmo2Config(LlamaConfig):
    """Llama config with OLMo2-7B defaults (post-norms, flat qk-norm)."""

    vocab_size: int = 100352
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 500000.0
    norm_after: bool = True
    qk_norm_flat: bool = True

    @classmethod
    def tiny(cls, **kw) -> "Olmo2Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)

    @classmethod
    def olmo2_7b(cls, **kw) -> "Olmo2Config":
        return cls(**kw)


def create_olmo2_model(config: Optional[Olmo2Config] = None, seed: int = 0, seq_len: int = 128):
    """A :class:`~accelerate_tpu.modeling.Model` running the llama module
    with OLMo2's post-norm layout and flat q/k norms."""
    return create_llama_model(config or Olmo2Config.tiny(), seed=seed, seq_len=seq_len)
