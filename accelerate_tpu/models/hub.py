"""Torch-free import of HuggingFace checkpoints into the model zoo.

The reference runs torch models directly; this framework's models are JAX
pytrees, so interop is a *weight import*: read safetensors (numpy, no torch
runtime), rename HF parameter paths to ours, transpose torch ``[out, in]``
linear weights to flax ``[in, out]`` kernels, and (for scanned models)
stack per-layer weights along the leading scan dim.

Entry points: :func:`load_hf_bert`, :func:`load_hf_llama`, or the low-level
``convert_hf_*_state`` on an already-loaded ``{name: np.ndarray}``.
"""

from __future__ import annotations

import json
import os
import re

import numpy as np


def read_safetensors_state(path: str) -> dict[str, np.ndarray]:
    """Load a safetensors file / shard-index / directory into numpy."""
    from safetensors.numpy import load_file

    state: dict[str, np.ndarray] = {}
    if os.path.isdir(path):
        index = [f for f in os.listdir(path) if f.endswith(".safetensors.index.json")]
        if index:
            with open(os.path.join(path, index[0])) as f:
                weight_map = json.load(f)["weight_map"]
            for shard in sorted(set(weight_map.values())):
                state.update(load_file(os.path.join(path, shard)))
        else:
            for f in sorted(os.listdir(path)):
                if f.endswith(".safetensors"):
                    state.update(load_file(os.path.join(path, f)))
    else:
        state = load_file(path)
    return state


def _strip_prefix(state: dict, prefixes: tuple[str, ...]) -> dict:
    out = {}
    for key, value in state.items():
        for prefix in prefixes:
            if key.startswith(prefix):
                key = key[len(prefix):]
                break
        out[key] = value
    return out


def _set(tree: dict, path: str, value: np.ndarray):
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


# --------------------------------------------------------------------- #
# BERT
# --------------------------------------------------------------------- #

_BERT_FIXED = {
    "embeddings.word_embeddings.weight": ("encoder/embeddings/word_embeddings/embedding", False),
    "embeddings.position_embeddings.weight": ("encoder/embeddings/position_embeddings/embedding", False),
    "embeddings.token_type_embeddings.weight": ("encoder/embeddings/token_type_embeddings/embedding", False),
    "embeddings.LayerNorm.weight": ("encoder/embeddings/norm/scale", False),
    "embeddings.LayerNorm.bias": ("encoder/embeddings/norm/bias", False),
    "pooler.dense.weight": ("pooler/kernel", True),
    "pooler.dense.bias": ("pooler/bias", False),
    "classifier.weight": ("classifier/kernel", True),
    "classifier.bias": ("classifier/bias", False),
}

_BERT_LAYER = {
    "attention.self.query.weight": ("attention/query/kernel", True),
    "attention.self.query.bias": ("attention/query/bias", False),
    "attention.self.key.weight": ("attention/key/kernel", True),
    "attention.self.key.bias": ("attention/key/bias", False),
    "attention.self.value.weight": ("attention/value/kernel", True),
    "attention.self.value.bias": ("attention/value/bias", False),
    "attention.output.dense.weight": ("attention/out/kernel", True),
    "attention.output.dense.bias": ("attention/out/bias", False),
    "attention.output.LayerNorm.weight": ("attention_norm/scale", False),
    "attention.output.LayerNorm.bias": ("attention_norm/bias", False),
    "intermediate.dense.weight": ("ffn/intermediate/kernel", True),
    "intermediate.dense.bias": ("ffn/intermediate/bias", False),
    "output.dense.weight": ("ffn/output/kernel", True),
    "output.dense.bias": ("ffn/output/bias", False),
    "output.LayerNorm.weight": ("ffn_norm/scale", False),
    "output.LayerNorm.bias": ("ffn_norm/bias", False),
}


def convert_hf_bert_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``bert-*`` (BertForSequenceClassification) -> our param pytree."""
    state = _strip_prefix(state, ("bert.",))
    tree: dict = {}
    for hf_key, (ours, transpose) in _BERT_FIXED.items():
        if hf_key in state:
            value = state[hf_key]
            _set(tree, ours, value.T if transpose else value)
    layer_re = re.compile(r"encoder\.layer\.(\d+)\.(.+)")
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        if rest in _BERT_LAYER:
            ours, transpose = _BERT_LAYER[rest]
            _set(tree, f"encoder/layer_{idx}/{ours}", value.T if transpose else value)
    return tree


def load_hf_bert(checkpoint_path: str, config=None):
    """Build a BERT Model and load HF weights into it."""
    import jax

    from .bert import BertConfig, create_bert_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_bert_state(state)
    model = create_bert_model(config or BertConfig.base())
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# Llama
# --------------------------------------------------------------------- #

_LLAMA_FIXED = {
    "model.embed_tokens.weight": ("embed_tokens/embedding", False),
    "model.norm.weight": ("final_norm/scale", False),
    "lm_head.weight": ("lm_head/kernel", True),
}

_LLAMA_LAYER = {
    "self_attn.q_proj.weight": ("attn/q_proj/kernel", True),
    "self_attn.k_proj.weight": ("attn/k_proj/kernel", True),
    "self_attn.v_proj.weight": ("attn/v_proj/kernel", True),
    # qkv biases (Qwen2); absent in llama/mistral checkpoints
    "self_attn.q_proj.bias": ("attn/q_proj/bias", False),
    "self_attn.k_proj.bias": ("attn/k_proj/bias", False),
    "self_attn.v_proj.bias": ("attn/v_proj/bias", False),
    "self_attn.o_proj.weight": ("attn/o_proj/kernel", True),
    "mlp.gate_proj.weight": ("mlp/gate_proj/kernel", True),
    "mlp.up_proj.weight": ("mlp/up_proj/kernel", True),
    "mlp.down_proj.weight": ("mlp/down_proj/kernel", True),
    "input_layernorm.weight": ("input_norm/scale", False),
    "post_attention_layernorm.weight": ("post_attn_norm/scale", False),
    # OLMo2 post-norm / Gemma2 sandwich layouts
    "post_feedforward_layernorm.weight": ("post_ffn_norm/scale", False),
    "pre_feedforward_layernorm.weight": ("pre_ffn_norm/scale", False),
    # q/k RMSNorm scales: Qwen3 [head_dim] (per-head), OLMo2 [H*head_dim]
    # (flat) — the loader's flat_qk_norm flag picks the re-pair grouping
    "self_attn.q_norm.weight": ("attn/q_norm/scale", False),
    "self_attn.k_norm.weight": ("attn/k_norm/scale", False),
}


def _rope_interleave_permute(kernel: np.ndarray, head_dim: int) -> np.ndarray:
    """Re-pair a q/k projection kernel from HF's half-split (``rotate_half``)
    rope convention to this zoo's interleaved convention.

    HF rotates dim pairs ``(j, j + D/2)``; our :func:`models.llama.rope`
    rotates ``(2j, 2j + 1)`` — importing HF weights without re-pairing
    silently rotates the WRONG coordinate pairs and attention logits
    drift (the same class of bug as HF's own Meta->HF ``permute`` in
    convert_llama_weights_to_hf.py). ``kernel`` is flax-layout
    ``[in, heads * head_dim]``."""
    if head_dim % 2 != 0:
        raise ValueError(
            f"rope re-pairing requires an even head_dim, got {head_dim} "
            f"(hidden_size / num_attention_heads in the HF config)"
        )
    in_dim, out_dim = kernel.shape
    heads = out_dim // head_dim
    k = kernel.reshape(in_dim, heads, head_dim)
    half = head_dim // 2
    perm = np.empty(head_dim, dtype=np.int64)
    perm[0::2] = np.arange(half)        # new 2j   <- old j        (first half)
    perm[1::2] = np.arange(half) + half  # new 2j+1 <- old j + D/2  (second half)
    return k[:, :, perm].reshape(in_dim, out_dim)


def convert_hf_llama_state(
    state: dict[str, np.ndarray],
    scan_layers: bool,
    num_heads: int,
    num_kv_heads: int,
    require: tuple = (),
    norm_after: bool = False,
    flat_qk_norm: bool = False,
) -> dict:
    """HF ``*ForCausalLM`` Llama -> our param pytree. With ``scan_layers``
    the per-layer weights are stacked along a leading layer dim to match
    the scanned module layout (``layers/block/...``). q/k kernels are
    re-paired for the interleaved rope convention (see
    :func:`_rope_interleave_permute`). ``require`` adds family-OPTIONAL
    param names (``attn/q_norm/scale`` etc.) to the every-layer
    completeness check — loaders pass the families their config demands,
    so a checkpoint missing them fails loudly instead of silently keeping
    random init (``_merge_into`` skips absent leaves)."""
    tree: dict = {}
    for hf_key, (ours, transpose) in _LLAMA_FIXED.items():
        if hf_key in state:
            value = state[hf_key]
            _set(tree, ours, value.T if transpose else value)
    # lm_head may be tied to embeddings in some checkpoints
    if "lm_head" not in tree and "model.embed_tokens.weight" in state:
        _set(tree, "lm_head/kernel", state["model.embed_tokens.weight"].T)

    layer_re = re.compile(r"model\.layers\.(\d+)\.(.+)")
    per_layer: dict[int, dict[str, np.ndarray]] = {}
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        if rest in _LLAMA_LAYER:
            ours, transpose = _LLAMA_LAYER[rest]
            converted = value.T if transpose else value
            if rest == "self_attn.q_proj.weight":
                converted = _rope_interleave_permute(converted, converted.shape[1] // num_heads)
            elif rest == "self_attn.k_proj.weight":
                converted = _rope_interleave_permute(converted, converted.shape[1] // num_kv_heads)
            elif rest == "self_attn.q_proj.bias":
                # biases rotate with their output channels: same re-pairing
                converted = _rope_interleave_permute(converted[None], len(converted) // num_heads)[0]
            elif rest == "self_attn.k_proj.bias":
                converted = _rope_interleave_permute(converted[None], len(converted) // num_kv_heads)[0]
            elif rest == "self_attn.q_norm.weight":
                # the norm scale multiplies per channel AFTER the (re-paired)
                # projection: Qwen3's [head_dim] re-pairs as one head, OLMo2's
                # flat [H*head_dim] re-pairs per head_dim group like a bias
                d = len(converted) // num_heads if flat_qk_norm else len(converted)
                converted = _rope_interleave_permute(converted[None], d)[0]
            elif rest == "self_attn.k_norm.weight":
                d = len(converted) // num_kv_heads if flat_qk_norm else len(converted)
                converted = _rope_interleave_permute(converted[None], d)[0]
            per_layer.setdefault(idx, {})[ours] = converted
    if not per_layer:
        return tree
    n_layers = max(per_layer) + 1
    # fail loudly on partial checkpoints (e.g. one shard of a sharded
    # save): the core weight families must be present in every layer —
    # a silent skip here would return a model with random kernels
    # biases (Qwen2) and q/k norm scales (Qwen3/OLMo2) are family-optional;
    # the layer norms swap with the convention (pre-norm: input+post_attn,
    # OLMo2 post-norm: post_attn+post_ffn, no input norms)
    required = {
        ours
        for ours, _ in _LLAMA_LAYER.values()
        if not ours.endswith(("/bias", "q_norm/scale", "k_norm/scale"))
        and ours not in ("input_norm/scale", "post_ffn_norm/scale", "pre_ffn_norm/scale")
    } | set(require)
    required |= {"post_ffn_norm/scale"} if norm_after else {"input_norm/scale"}
    for i in range(n_layers):
        missing = required - set(per_layer.get(i, {}))
        if missing:
            raise ValueError(
                f"layer {i} is missing {sorted(missing)} — partial checkpoint? "
                "pass the checkpoint directory (or its index), not a single shard"
            )
    # family-optional params (biases, q/k norms) must still be all-or-none
    # across layers: stacking from layer 0's key set would silently drop a
    # param present only in later layers (or KeyError on one missing later)
    union = set().union(*(per_layer[i].keys() for i in range(n_layers)))
    for name in union:
        holes = [i for i in range(n_layers) if name not in per_layer[i]]
        if holes:
            raise ValueError(
                f"param {name!r} present in some layers but missing from layers "
                f"{holes} — partial checkpoint? pass the full directory/index"
            )
    if scan_layers:
        # stack only params the checkpoint actually has (biases are
        # family-dependent)
        for name in sorted(union):
            stacked = np.stack([per_layer[i][name] for i in range(n_layers)])
            _set(tree, f"layers/block/{name}", stacked)
    else:
        for i in range(n_layers):
            for name, value in per_layer[i].items():
                _set(tree, f"layer_{i}/{name}", value)
    return tree


def load_hf_llama(checkpoint_path: str, config=None):
    import jax

    from .llama import LlamaConfig, create_llama_model

    state = read_safetensors_state(checkpoint_path)
    config = config or LlamaConfig.llama2_7b()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
    )
    model = create_llama_model(config)
    _merge_into(model, tree)
    return model


def split_phi3_fused_state(state: dict[str, np.ndarray], num_heads: int, num_kv_heads: int) -> dict:
    """Rewrite Phi-3's fused tensors into the llama state-dict layout:
    ``qkv_proj`` -> q/k/v (row-split in torch [out, in] orientation, so
    the head width divides the fused out dim) and ``gate_up_proj`` ->
    gate/up (first half gate — HF's chunk(2) order). The result feeds
    :func:`convert_hf_llama_state` unchanged, rope re-pairing included."""
    out: dict[str, np.ndarray] = {}
    for key, value in state.items():
        if key.endswith("self_attn.qkv_proj.weight"):
            prefix = key[: -len("qkv_proj.weight")]
            hd = value.shape[0] // (num_heads + 2 * num_kv_heads)
            q, k, v = np.split(value, [num_heads * hd, (num_heads + num_kv_heads) * hd], axis=0)
            out[prefix + "q_proj.weight"] = q
            out[prefix + "k_proj.weight"] = k
            out[prefix + "v_proj.weight"] = v
        elif key.endswith("mlp.gate_up_proj.weight"):
            prefix = key[: -len("gate_up_proj.weight")]
            gate, up = np.split(value, 2, axis=0)
            out[prefix + "gate_proj.weight"] = gate
            out[prefix + "up_proj.weight"] = up
        else:
            out[key] = value
    return out


def load_hf_phi3(checkpoint_path: str, config=None):
    """HF Phi-3 checkpoints are llama-layout after splitting the fused
    qkv_proj / gate_up_proj tensors (the module keeps separate
    projections — XLA fuses the matmuls on TPU regardless)."""
    from .phi3 import Phi3Config, create_phi3_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Phi3Config.phi3_mini_4k()
    state = split_phi3_fused_state(state, config.num_attention_heads, config.num_key_value_heads)
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
    )
    model = create_phi3_model(config)
    _merge_into(model, tree)
    return model


def load_hf_gemma(checkpoint_path: str, config=None):
    """HF Gemma checkpoints are llama-layout (the rope re-pairing derives
    head width from the projection shapes, covering the explicit
    head_dim); the LM head is always tied (importer fallback) and the
    (1+scale) norm offsets import verbatim."""
    from .gemma import GemmaConfig, create_gemma_model

    state = read_safetensors_state(checkpoint_path)
    config = config or GemmaConfig.gemma_2b()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
    )
    model = create_gemma_model(config)
    _merge_into(model, tree)
    return model


def load_hf_gemma2(checkpoint_path: str, config=None):
    """HF Gemma2 checkpoints are llama-layout plus the sandwich-norm keys
    (pre/post feedforward layernorms); head always tied, (1+scale) norm
    offsets import verbatim."""
    from .gemma2 import Gemma2Config, create_gemma2_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Gemma2Config.gemma2_9b()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        require=("pre_ffn_norm/scale", "post_ffn_norm/scale") if config.sandwich_norm else (),
    )
    model = create_gemma2_model(config)
    _merge_into(model, tree)
    return model


def load_hf_gemma3(checkpoint_path: str, config=None):
    """HF Gemma3 text checkpoints: llama layout + sandwich-norm keys +
    per-head q/k norm scales ([head_dim], re-paired like Qwen3's)."""
    from .gemma3 import Gemma3Config, create_gemma3_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Gemma3Config.gemma3_1b()
    require = ()
    if config.sandwich_norm:
        require += ("pre_ffn_norm/scale", "post_ffn_norm/scale")
    if config.qk_norm:
        require += ("attn/q_norm/scale", "attn/k_norm/scale")
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        require=require,
    )
    model = create_gemma3_model(config)
    _merge_into(model, tree)
    return model


def load_hf_qwen2(checkpoint_path: str, config=None):
    """HF Qwen2/Qwen2.5 checkpoints are llama-layout plus q/k/v bias
    vectors (re-paired for the rope convention like their kernels);
    small variants tie lm_head to the embeddings (importer fallback)."""
    from .qwen2 import Qwen2Config, create_qwen2_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Qwen2Config.qwen2_7b()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        require=(
            ("attn/q_proj/bias", "attn/k_proj/bias", "attn/v_proj/bias")
            if config.qkv_bias
            else ()
        ),
    )
    model = create_qwen2_model(config)
    _merge_into(model, tree)
    return model


def load_hf_qwen3(checkpoint_path: str, config=None):
    """HF Qwen3 checkpoints are llama-layout with per-head q/k norm scales
    (re-paired for the interleaved rope convention) and no qkv biases;
    small variants tie lm_head to the embeddings (importer fallback)."""
    from .qwen3 import Qwen3Config, create_qwen3_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Qwen3Config.qwen3_8b()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        require=("attn/q_norm/scale", "attn/k_norm/scale") if config.qk_norm else (),
    )
    model = create_qwen3_model(config)
    _merge_into(model, tree)
    return model


def load_hf_olmo2(checkpoint_path: str, config=None):
    """HF OLMo2 checkpoints are llama-layout with post-norm keys
    (post_attention/post_feedforward, no input norms) and flat q/k norm
    scales re-paired per head_dim group for the interleaved rope."""
    from .olmo2 import Olmo2Config, create_olmo2_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Olmo2Config.olmo2_7b()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        require=("attn/q_norm/scale", "attn/k_norm/scale") if config.qk_norm_flat else (),
        norm_after=config.norm_after,
        flat_qk_norm=config.qk_norm_flat,
    )
    model = create_olmo2_model(config)
    _merge_into(model, tree)
    return model


def load_hf_mistral(checkpoint_path: str, config=None):
    """HF Mistral checkpoints use the llama state-dict layout verbatim
    (model.layers.N.self_attn/mlp/...); only the config differs — the
    band width rides in ``MistralConfig.sliding_window``. Default config
    is Mistral-7B-**v0.1**; pass ``MistralConfig.mistral_7b_v3()`` for
    v0.2/v0.3 weights (different theta, no window)."""
    from .mistral import MistralConfig, create_mistral_model

    state = read_safetensors_state(checkpoint_path)
    config = config or MistralConfig.mistral_7b_v1()
    tree = convert_hf_llama_state(
        state,
        scan_layers=config.scan_layers,
        num_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
    )
    model = create_mistral_model(config)
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# GPT-2
# --------------------------------------------------------------------- #

_GPT2_LAYER = {
    "ln_1.weight": "ln_1/scale",
    "ln_1.bias": "ln_1/bias",
    "ln_2.weight": "ln_2/scale",
    "ln_2.bias": "ln_2/bias",
    "attn.c_proj.weight": "attn/o_proj/kernel",
    "attn.c_proj.bias": "attn/o_proj/bias",
    "mlp.c_fc.weight": "mlp/fc_in/kernel",
    "mlp.c_fc.bias": "mlp/fc_in/bias",
    "mlp.c_proj.weight": "mlp/fc_out/kernel",
    "mlp.c_proj.bias": "mlp/fc_out/bias",
}


def convert_hf_gpt2_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``gpt2`` -> our param pytree. HF GPT-2 uses Conv1D layers whose
    weights are already ``[in, out]`` (no transpose), and a fused
    ``c_attn`` that we split into q/k/v thirds."""
    state = _strip_prefix(state, ("transformer.",))
    tree: dict = {}
    if "wte.weight" in state:
        _set(tree, "wte/embedding", state["wte.weight"])
    if "wpe.weight" in state:
        _set(tree, "wpe/embedding", state["wpe.weight"])
    if "ln_f.weight" in state:
        _set(tree, "ln_f/scale", state["ln_f.weight"])
        _set(tree, "ln_f/bias", state["ln_f.bias"])
    # HF gpt2 ties the head to wte and ships no lm_head tensor; provide the
    # tied fallback for untied configs (same pattern as llama, above)
    if "wte.weight" in state:
        _set(tree, "lm_head/kernel", state["wte.weight"].T)
    layer_re = re.compile(r"h\.(\d+)\.(.+)")
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        if rest in _GPT2_LAYER:
            _set(tree, f"layer_{idx}/{_GPT2_LAYER[rest]}", value)
        elif rest == "attn.c_attn.weight":
            d = value.shape[0]
            for j, name in enumerate(("q_proj", "k_proj", "v_proj")):
                _set(tree, f"layer_{idx}/attn/{name}/kernel", value[:, j * d:(j + 1) * d])
        elif rest == "attn.c_attn.bias":
            d = value.shape[0] // 3
            for j, name in enumerate(("q_proj", "k_proj", "v_proj")):
                _set(tree, f"layer_{idx}/attn/{name}/bias", value[j * d:(j + 1) * d])
    return tree


def load_hf_gpt2(checkpoint_path: str, config=None):
    from .gpt2 import GPT2Config, create_gpt2_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_gpt2_state(state)
    model = create_gpt2_model(config or GPT2Config.small())
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# T5
# --------------------------------------------------------------------- #

_T5_SELF = {
    "q.weight": ("q_proj/kernel", True),
    "k.weight": ("k_proj/kernel", True),
    "v.weight": ("v_proj/kernel", True),
    "o.weight": ("o_proj/kernel", True),
    "relative_attention_bias.weight": ("relative_bias/embedding", False),
}

_T5_FFN = {
    "DenseReluDense.wi.weight": ("ffn/wi/kernel", True),
    "DenseReluDense.wo.weight": ("ffn/wo/kernel", True),
}


def convert_hf_t5_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``t5-*`` -> our param pytree (encoder.block.N.layer.{0,1} /
    decoder.block.N.layer.{0,1,2} structure flattened to our names)."""
    tree: dict = {}
    if "shared.weight" in state:
        _set(tree, "shared/embedding", state["shared.weight"])
    if "lm_head.weight" in state:
        _set(tree, "lm_head/kernel", state["lm_head.weight"].T)
    if "encoder.final_layer_norm.weight" in state:
        _set(tree, "enc_final_norm/scale", state["encoder.final_layer_norm.weight"])
    if "decoder.final_layer_norm.weight" in state:
        _set(tree, "dec_final_norm/scale", state["decoder.final_layer_norm.weight"])

    pat = re.compile(r"(encoder|decoder)\.block\.(\d+)\.layer\.(\d+)\.(.+)")
    for key, value in state.items():
        m = pat.match(key)
        if not m:
            continue
        stack, idx, sub, rest = m.group(1), int(m.group(2)), int(m.group(3)), m.group(4)
        enc = stack == "encoder"
        prefix = f"{'enc' if enc else 'dec'}_layer_{idx}"
        if enc:
            # layer.0 = self-attn, layer.1 = ffn
            attn_name, norms = "attn", {0: "ln_attn", 1: "ln_ffn"}
        else:
            # layer.0 = self-attn, layer.1 = cross-attn, layer.2 = ffn
            attn_name = "self_attn" if sub == 0 else "cross_attn"
            norms = {0: "ln_self", 1: "ln_cross", 2: "ln_ffn"}
        if rest == "layer_norm.weight":
            _set(tree, f"{prefix}/{norms[sub]}/scale", value)
            continue
        for hf_prefix in ("SelfAttention.", "EncDecAttention."):
            if rest.startswith(hf_prefix):
                name, transpose = _T5_SELF[rest[len(hf_prefix):]]
                _set(tree, f"{prefix}/{attn_name}/{name}", value.T if transpose else value)
                break
        else:
            if rest in _T5_FFN:
                name, transpose = _T5_FFN[rest]
                _set(tree, f"{prefix}/{name}", value.T if transpose else value)
    return tree


def load_hf_t5(checkpoint_path: str, config=None):
    from .t5 import T5Config, create_t5_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_t5_state(state)
    model = create_t5_model(config or T5Config.small())
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# ViT
# --------------------------------------------------------------------- #

_VIT_BLOCK = {
    "attention.attention.query.weight": ("attention/query/kernel", True),
    "attention.attention.query.bias": ("attention/query/bias", False),
    "attention.attention.key.weight": ("attention/key/kernel", True),
    "attention.attention.key.bias": ("attention/key/bias", False),
    "attention.attention.value.weight": ("attention/value/kernel", True),
    "attention.attention.value.bias": ("attention/value/bias", False),
    "attention.output.dense.weight": ("attention/out/kernel", True),
    "attention.output.dense.bias": ("attention/out/bias", False),
    "intermediate.dense.weight": ("mlp/up/kernel", True),
    "intermediate.dense.bias": ("mlp/up/bias", False),
    "output.dense.weight": ("mlp/down/kernel", True),
    "output.dense.bias": ("mlp/down/bias", False),
    "layernorm_before.weight": ("norm1/scale", False),
    "layernorm_before.bias": ("norm1/bias", False),
    "layernorm_after.weight": ("norm2/scale", False),
    "layernorm_after.bias": ("norm2/bias", False),
}


def convert_hf_vit_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``ViTForImageClassification`` -> our param pytree. The patch
    conv transposes torch OIHW -> flax HWIO."""
    state = _strip_prefix(state, ("vit.",))
    tree: dict = {}
    if "embeddings.cls_token" in state:
        _set(tree, "cls_token", state["embeddings.cls_token"])
    if "embeddings.position_embeddings" in state:
        _set(tree, "pos_embed", state["embeddings.position_embeddings"])
    if "embeddings.patch_embeddings.projection.weight" in state:
        w = state["embeddings.patch_embeddings.projection.weight"]  # [d, 3, p, p]
        _set(tree, "patch_embed/kernel", w.transpose(2, 3, 1, 0))
    if "embeddings.patch_embeddings.projection.bias" in state:
        _set(tree, "patch_embed/bias", state["embeddings.patch_embeddings.projection.bias"])
    if "layernorm.weight" in state:
        _set(tree, "final_norm/scale", state["layernorm.weight"])
    if "layernorm.bias" in state:
        _set(tree, "final_norm/bias", state["layernorm.bias"])
    if "classifier.weight" in state:
        _set(tree, "head/kernel", state["classifier.weight"].T)
    if "classifier.bias" in state:
        _set(tree, "head/bias", state["classifier.bias"])

    layer_re = re.compile(r"encoder\.layer\.(\d+)\.(.+)")
    for key, value in state.items():
        m = layer_re.match(key)
        if m and m.group(2) in _VIT_BLOCK:
            name, transpose = _VIT_BLOCK[m.group(2)]
            _set(tree, f"block_{int(m.group(1))}/{name}", value.T if transpose else value)
    return tree


def load_hf_vit(checkpoint_path: str, config=None):
    from .vit import ViTConfig, create_vit_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_vit_state(state)
    model = create_vit_model(config or ViTConfig.base())
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# Mixtral
# --------------------------------------------------------------------- #

_MIXTRAL_ATTN = {
    "self_attn.q_proj.weight": "attn/q_proj/kernel",
    "self_attn.k_proj.weight": "attn/k_proj/kernel",
    "self_attn.v_proj.weight": "attn/v_proj/kernel",
    "self_attn.o_proj.weight": "attn/o_proj/kernel",
}


_MIXTRAL_EXPERT_NAMES = {"w1": "gate_proj", "w3": "up_proj", "w2": "down_proj"}


def convert_hf_mixtral_state(
    state: dict[str, np.ndarray],
    num_heads: int,
    num_kv_heads: int,
    *,
    router_key: str = "block_sparse_moe.gate.weight",
    expert_re: str = r"block_sparse_moe\.experts\.(\d+)\.(w[123])\.weight",
    expert_names: Optional[dict] = None,
    qk_norm: bool = False,
) -> dict:
    """HF MoE ``*ForCausalLM`` -> our param pytree: llama-style attention
    (q/k re-paired for interleaved rope), per-expert kernels stacked into
    ``experts/{gate,up,down}_proj`` with a leading expert dim, the router
    transposed to ``router/kernel``. One skeleton serves Mixtral (defaults)
    and Qwen3-MoE (``mlp.gate`` router, ``gate/up/down_proj`` expert keys,
    ``qk_norm=True`` for the re-paired per-head norm scales). Every layer
    must carry the full attention/norm/router/expert family — a partial
    checkpoint fails loudly instead of silently keeping random init
    (``_merge_into`` skips absent leaves)."""
    expert_names = expert_names if expert_names is not None else _MIXTRAL_EXPERT_NAMES
    tree: dict = {}
    if "model.embed_tokens.weight" in state:
        _set(tree, "embed_tokens/embedding", state["model.embed_tokens.weight"])
    if "model.norm.weight" in state:
        _set(tree, "final_norm/scale", state["model.norm.weight"])
    if "lm_head.weight" in state:
        _set(tree, "lm_head/kernel", state["lm_head.weight"].T)
    elif "model.embed_tokens.weight" in state:
        _set(tree, "lm_head/kernel", state["model.embed_tokens.weight"].T)

    layer_re = re.compile(r"model\.layers\.(\d+)\.(.+)")
    expert_pat = re.compile(expert_re)
    experts: dict[tuple, dict[int, np.ndarray]] = {}
    seen: dict[int, set] = {}
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        prefix = f"layer_{idx}"
        got = seen.setdefault(idx, set())
        if rest in _MIXTRAL_ATTN:
            kernel = value.T
            if rest == "self_attn.q_proj.weight":
                kernel = _rope_interleave_permute(kernel, kernel.shape[1] // num_heads)
            elif rest == "self_attn.k_proj.weight":
                kernel = _rope_interleave_permute(kernel, kernel.shape[1] // num_kv_heads)
            _set(tree, f"{prefix}/{_MIXTRAL_ATTN[rest]}", kernel)
            got.add(_MIXTRAL_ATTN[rest])
        elif qk_norm and rest in ("self_attn.q_norm.weight", "self_attn.k_norm.weight"):
            # [head_dim] per-head scales re-pair as one head (see qwen3.py)
            which = "q_norm" if "q_norm" in rest else "k_norm"
            _set(tree, f"{prefix}/attn/{which}/scale", _rope_interleave_permute(value[None], len(value))[0])
            got.add(f"attn/{which}/scale")
        elif rest == "input_layernorm.weight":
            _set(tree, f"{prefix}/input_norm/scale", value)
            got.add("input_norm/scale")
        elif rest == "post_attention_layernorm.weight":
            _set(tree, f"{prefix}/post_attn_norm/scale", value)
            got.add("post_attn_norm/scale")
        elif rest == router_key:
            _set(tree, f"{prefix}/moe/router/kernel", value.T)
            got.add("moe/router/kernel")
        else:
            em = expert_pat.fullmatch(rest)
            if em:
                # mixtral: w1 = gate (silu branch), w3 = up, w2 = down;
                # qwen3-moe names map through identically. torch [out, in]
                name = expert_names.get(em.group(2), em.group(2))
                experts.setdefault((idx, name), {})[int(em.group(1))] = value.T
    if not seen:
        return tree
    n_layers = max(seen) + 1
    required = set(_MIXTRAL_ATTN.values()) | {
        "input_norm/scale", "post_attn_norm/scale", "moe/router/kernel",
    }
    if qk_norm:
        required |= {"attn/q_norm/scale", "attn/k_norm/scale"}
    for i in range(n_layers):
        missing = required - seen.get(i, set())
        missing |= {
            f"moe/experts/{name}"
            for name in ("gate_proj", "up_proj", "down_proj")
            if (i, name) not in experts
        }
        if missing:
            raise ValueError(
                f"layer {i} is missing {sorted(missing)} — partial checkpoint? "
                "pass the checkpoint directory (or its index), not a single shard"
            )
    for (idx, name), per_expert in experts.items():
        n_exp = max(per_expert) + 1
        holes = [e for e in range(n_exp) if e not in per_expert]
        if holes:
            raise ValueError(f"layer {idx} {name}: experts {holes} missing — partial checkpoint?")
        stacked = np.stack([per_expert[i] for i in range(n_exp)])
        _set(tree, f"layer_{idx}/moe/experts/{name}", stacked)
    return tree


def load_hf_mixtral(checkpoint_path: str, config=None):
    from .mixtral import MixtralConfig, create_mixtral_model

    state = read_safetensors_state(checkpoint_path)
    config = config or MixtralConfig()
    tree = convert_hf_mixtral_state(
        state, num_heads=config.num_attention_heads, num_kv_heads=config.num_key_value_heads
    )
    model = create_mixtral_model(config)
    _merge_into(model, tree)
    return model


def convert_hf_qwen3_moe_state(state: dict[str, np.ndarray], num_heads: int, num_kv_heads: int) -> dict:
    """HF ``Qwen3MoeForCausalLM`` -> our param pytree: the mixtral skeleton
    with Qwen3's key names (``mlp.gate`` router, ``gate/up/down_proj``
    expert kernels) and the per-head q/k norm scales re-paired for
    interleaved rope."""
    return convert_hf_mixtral_state(
        state,
        num_heads,
        num_kv_heads,
        router_key="mlp.gate.weight",
        expert_re=r"mlp\.experts\.(\d+)\.(gate_proj|up_proj|down_proj)\.weight",
        expert_names={},
        qk_norm=True,
    )


def load_hf_qwen3_moe(checkpoint_path: str, config=None):
    """HF Qwen3-MoE checkpoints through the mixtral-core model."""
    from .qwen3_moe import Qwen3MoeConfig, create_qwen3_moe_model

    state = read_safetensors_state(checkpoint_path)
    config = config or Qwen3MoeConfig.qwen3_30b_a3b()
    tree = convert_hf_qwen3_moe_state(
        state, num_heads=config.num_attention_heads, num_kv_heads=config.num_key_value_heads
    )
    model = create_qwen3_moe_model(config)
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# GPT-NeoX
# --------------------------------------------------------------------- #

_NEOX_LAYER = {
    "input_layernorm.weight": ("input_norm/scale", False),
    "input_layernorm.bias": ("input_norm/bias", False),
    "post_attention_layernorm.weight": ("post_attn_norm/scale", False),
    "post_attention_layernorm.bias": ("post_attn_norm/bias", False),
    "attention.dense.weight": ("attn/o_proj/kernel", True),
    "attention.dense.bias": ("attn/o_proj/bias", False),
    "mlp.dense_h_to_4h.weight": ("mlp/fc_in/kernel", True),
    "mlp.dense_h_to_4h.bias": ("mlp/fc_in/bias", False),
    "mlp.dense_4h_to_h.weight": ("mlp/fc_out/kernel", True),
    "mlp.dense_4h_to_h.bias": ("mlp/fc_out/bias", False),
}


def _partial_rope_interleave_permute(kernel: np.ndarray, head_dim: int, rotary_dims: int) -> np.ndarray:
    """:func:`_rope_interleave_permute` restricted to the first
    ``rotary_dims`` of each head (GPT-NeoX ``rotary_pct``); the unrotated
    tail keeps its order."""
    if rotary_dims >= head_dim:
        return _rope_interleave_permute(kernel, head_dim)
    if rotary_dims % 2 != 0:
        raise ValueError(
            f"rope re-pairing requires an even rotary prefix, got rotary_dims={rotary_dims} "
            f"(int(head_dim * rotary_pct) in the HF GPT-NeoX config)"
        )
    in_dim, out_dim = kernel.shape
    heads = out_dim // head_dim
    k = kernel.reshape(in_dim, heads, head_dim)
    half = rotary_dims // 2
    perm = np.arange(head_dim)
    perm[0:rotary_dims:2] = np.arange(half)
    perm[1:rotary_dims:2] = np.arange(half) + half
    return k[:, :, perm].reshape(in_dim, out_dim)


def convert_hf_gptneox_state(state: dict[str, np.ndarray], num_heads: int, rotary_pct: float) -> dict:
    """HF ``GPTNeoXForCausalLM`` -> our param pytree. The fused
    ``attention.query_key_value`` [3*hidden, hidden] is head-major
    ([heads, 3, head_dim] on the out dim) and splits into q/k/v; q/k are
    re-paired for the interleaved rope convention on the rotary prefix."""
    state = _strip_prefix(state, ("gpt_neox.",))
    tree: dict = {}
    if "embed_in.weight" in state:
        _set(tree, "embed_in/embedding", state["embed_in.weight"])
    if "embed_out.weight" in state:
        _set(tree, "embed_out/kernel", state["embed_out.weight"].T)
    if "final_layer_norm.weight" in state:
        _set(tree, "final_norm/scale", state["final_layer_norm.weight"])
    if "final_layer_norm.bias" in state:
        _set(tree, "final_norm/bias", state["final_layer_norm.bias"])

    layer_re = re.compile(r"layers\.(\d+)\.(.+)")
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        prefix = f"layer_{idx}"
        if rest in _NEOX_LAYER:
            name, transpose = _NEOX_LAYER[rest]
            _set(tree, f"{prefix}/{name}", value.T if transpose else value)
        elif rest == "attention.query_key_value.weight":
            hidden = value.shape[1]
            head_dim = hidden // num_heads
            rotary_dims = int(head_dim * rotary_pct)
            # [3H, hidden] out-dim layout is [heads, 3, head_dim]
            w = value.reshape(num_heads, 3, head_dim, hidden)
            for j, proj in enumerate(("q_proj", "k_proj", "v_proj")):
                kernel = w[:, j].reshape(hidden, hidden).T  # -> [in, out]
                if proj in ("q_proj", "k_proj"):
                    kernel = _partial_rope_interleave_permute(kernel, head_dim, rotary_dims)
                _set(tree, f"{prefix}/attn/{proj}/kernel", kernel)
        elif rest == "attention.query_key_value.bias":
            hidden = value.shape[0] // 3
            head_dim = hidden // num_heads
            rotary_dims = int(head_dim * rotary_pct)
            b = value.reshape(num_heads, 3, head_dim)
            for j, proj in enumerate(("q_proj", "k_proj", "v_proj")):
                bias = b[:, j].reshape(hidden)
                if proj in ("q_proj", "k_proj"):
                    bias = _partial_rope_interleave_permute(bias[None], head_dim, rotary_dims)[0]
                _set(tree, f"{prefix}/attn/{proj}/bias", bias)
    return tree


def load_hf_gptneox(checkpoint_path: str, config=None):
    from .gptneox import GPTNeoXConfig, create_gptneox_model

    state = read_safetensors_state(checkpoint_path)
    config = config or GPTNeoXConfig.neox_20b()
    tree = convert_hf_gptneox_state(
        state, num_heads=config.num_attention_heads, rotary_pct=config.rotary_pct
    )
    model = create_gptneox_model(config)
    _merge_into(model, tree)
    return model


def _merge_into(model, tree: dict):
    """Replace model params with imported values (shape-checked; values not
    present keep their initialisation)."""
    import jax

    from ..parallel.sharding import path_str

    flat_imported = {}

    def flatten(node, prefix=""):
        if isinstance(node, dict):
            for k, v in node.items():
                flatten(v, f"{prefix}{k}/")
        else:
            flat_imported[prefix[:-1]] = node

    flatten(tree)

    leaves, treedef = jax.tree_util.tree_flatten_with_path(model.params)
    new_leaves = []
    imported = 0
    for kp, old in leaves:
        key = path_str(kp)
        if key in flat_imported:
            new = np.asarray(flat_imported[key])
            if tuple(new.shape) != tuple(old.shape):
                raise ValueError(f"shape mismatch importing {key}: {new.shape} vs {old.shape}")
            new_leaves.append(new.astype(old.dtype))
            imported += 1
        else:
            new_leaves.append(old)
    model.params = jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(model.params), new_leaves)
    model.imported_weight_count = imported
    return model


# --------------------------------------------------------------------- #
# Whisper
# --------------------------------------------------------------------- #

_WHISPER_ATTN = {
    "q_proj.weight": ("q_proj/kernel", True),
    "q_proj.bias": ("q_proj/bias", False),
    "k_proj.weight": ("k_proj/kernel", True),
    "v_proj.weight": ("v_proj/kernel", True),
    "v_proj.bias": ("v_proj/bias", False),
    "out_proj.weight": ("out_proj/kernel", True),
    "out_proj.bias": ("out_proj/bias", False),
}

_WHISPER_NORMS = {
    "self_attn_layer_norm": "ln_self",
    "encoder_attn_layer_norm": "ln_cross",
    "final_layer_norm": "ln_ffn",
}


def convert_hf_whisper_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``WhisperForConditionalGeneration`` -> our param pytree. Torch
    Conv1d weights [out, in, k] transpose to flax [k, in, out]; the decoder
    output projection is tied to ``embed_tokens`` (proj_out has no weight
    of its own in the checkpoint)."""
    state = _strip_prefix(state, ("model.",))
    tree: dict = {}
    for conv in ("conv1", "conv2"):
        if f"encoder.{conv}.weight" in state:
            _set(tree, f"{conv}/kernel", state[f"encoder.{conv}.weight"].transpose(2, 1, 0))
            _set(tree, f"{conv}/bias", state[f"encoder.{conv}.bias"])
    # encoder.embed_positions is the frozen sinusoid table — our model
    # computes it (models/whisper.py sinusoids), so it is not imported
    if "decoder.embed_positions.weight" in state:
        _set(tree, "dec_pos/embedding", state["decoder.embed_positions.weight"])
    if "decoder.embed_tokens.weight" in state:
        _set(tree, "embed_tokens/embedding", state["decoder.embed_tokens.weight"])
    for stack, out_name in (("encoder", "enc_final_norm"), ("decoder", "dec_final_norm")):
        if f"{stack}.layer_norm.weight" in state:
            _set(tree, f"{out_name}/scale", state[f"{stack}.layer_norm.weight"])
            _set(tree, f"{out_name}/bias", state[f"{stack}.layer_norm.bias"])

    pat = re.compile(r"(encoder|decoder)\.layers\.(\d+)\.(.+)")
    for key, value in state.items():
        m = pat.match(key)
        if not m:
            continue
        stack, idx, rest = m.group(1), int(m.group(2)), m.group(3)
        prefix = f"{'enc' if stack == 'encoder' else 'dec'}_layer_{idx}"
        for hf_attn, our_attn in (("self_attn.", "self_attn"), ("encoder_attn.", "cross_attn")):
            if rest.startswith(hf_attn):
                name, transpose = _WHISPER_ATTN[rest[len(hf_attn):]]
                _set(tree, f"{prefix}/{our_attn}/{name}", value.T if transpose else value)
                break
        else:
            for hf_norm, our_norm in _WHISPER_NORMS.items():
                if rest.startswith(hf_norm + "."):
                    part = "scale" if rest.endswith("weight") else "bias"
                    _set(tree, f"{prefix}/{our_norm}/{part}", value)
                    break
            else:
                for fc in ("fc1", "fc2"):
                    if rest == f"{fc}.weight":
                        _set(tree, f"{prefix}/{fc}/kernel", value.T)
                    elif rest == f"{fc}.bias":
                        _set(tree, f"{prefix}/{fc}/bias", value)
    return tree


def load_hf_whisper(checkpoint_path: str, config=None):
    from .whisper import WhisperConfig, create_whisper_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_whisper_state(state)
    cfg = config or WhisperConfig()
    model = create_whisper_model(cfg, n_frames=2 * cfg.max_source_positions, dec_len=8)
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# CLIP
# --------------------------------------------------------------------- #

_CLIP_BLOCK = {
    "self_attn.q_proj.weight": ("q_proj/kernel", True),
    "self_attn.q_proj.bias": ("q_proj/bias", False),
    "self_attn.k_proj.weight": ("k_proj/kernel", True),
    "self_attn.k_proj.bias": ("k_proj/bias", False),
    "self_attn.v_proj.weight": ("v_proj/kernel", True),
    "self_attn.v_proj.bias": ("v_proj/bias", False),
    "self_attn.out_proj.weight": ("out_proj/kernel", True),
    "self_attn.out_proj.bias": ("out_proj/bias", False),
    "layer_norm1.weight": ("ln1/scale", False),
    "layer_norm1.bias": ("ln1/bias", False),
    "layer_norm2.weight": ("ln2/scale", False),
    "layer_norm2.bias": ("ln2/bias", False),
    "mlp.fc1.weight": ("fc1/kernel", True),
    "mlp.fc1.bias": ("fc1/bias", False),
    "mlp.fc2.weight": ("fc2/kernel", True),
    "mlp.fc2.bias": ("fc2/bias", False),
}

_CLIP_FIXED = {
    "vision_model.embeddings.class_embedding": ("vision/class_embedding", False),
    "vision_model.embeddings.position_embedding.weight": ("vision/pos_embed/embedding", False),
    # yes, HF really spells it "pre_layrnorm"
    "vision_model.pre_layrnorm.weight": ("vision/pre_norm/scale", False),
    "vision_model.pre_layrnorm.bias": ("vision/pre_norm/bias", False),
    "vision_model.post_layernorm.weight": ("vision/post_norm/scale", False),
    "vision_model.post_layernorm.bias": ("vision/post_norm/bias", False),
    "text_model.embeddings.token_embedding.weight": ("text/token_embed/embedding", False),
    "text_model.embeddings.position_embedding.weight": ("text/pos_embed/embedding", False),
    "text_model.final_layer_norm.weight": ("text/final_norm/scale", False),
    "text_model.final_layer_norm.bias": ("text/final_norm/bias", False),
    "visual_projection.weight": ("visual_projection/kernel", True),
    "text_projection.weight": ("text_projection/kernel", True),
    "logit_scale": ("logit_scale", False),
}


def convert_hf_clip_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``CLIPModel`` -> our param pytree. Conv patch embedding
    [d, 3, p, p] transposes to flax [p, p, 3, d]."""
    tree: dict = {}
    if "vision_model.embeddings.patch_embedding.weight" in state:
        _set(
            tree,
            "vision/patch_embed/kernel",
            state["vision_model.embeddings.patch_embedding.weight"].transpose(2, 3, 1, 0),
        )
    for hf_key, (ours, transpose) in _CLIP_FIXED.items():
        if hf_key in state:
            _set(tree, ours, state[hf_key].T if transpose else state[hf_key])
    pat = re.compile(r"(vision|text)_model\.encoder\.layers\.(\d+)\.(.+)")
    for key, value in state.items():
        m = pat.match(key)
        if not m:
            continue
        tower, idx, rest = m.group(1), int(m.group(2)), m.group(3)
        if rest in _CLIP_BLOCK:
            ours, transpose = _CLIP_BLOCK[rest]
            _set(tree, f"{tower}/block_{idx}/{ours}", value.T if transpose else value)
    return tree


def load_hf_clip(checkpoint_path: str, config=None):
    from .clip import CLIPConfig, create_clip_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_clip_state(state)
    model = create_clip_model(config or CLIPConfig())
    _merge_into(model, tree)
    return model
