"""Torch-free import of HuggingFace checkpoints into the model zoo.

The reference runs torch models directly; this framework's models are JAX
pytrees, so interop is a *weight import*: read safetensors (numpy, no torch
runtime), rename HF parameter paths to ours, transpose torch ``[out, in]``
linear weights to flax ``[in, out]`` kernels, and (for scanned models)
stack per-layer weights along the leading scan dim.

Entry points: :func:`load_hf_bert`, :func:`load_hf_llama`, or the low-level
``convert_hf_*_state`` on an already-loaded ``{name: np.ndarray}``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

import numpy as np


def read_safetensors_state(path: str) -> dict[str, np.ndarray]:
    """Load a safetensors file / shard-index / directory into numpy."""
    from safetensors.numpy import load_file

    state: dict[str, np.ndarray] = {}
    if os.path.isdir(path):
        index = [f for f in os.listdir(path) if f.endswith(".safetensors.index.json")]
        if index:
            with open(os.path.join(path, index[0])) as f:
                weight_map = json.load(f)["weight_map"]
            for shard in sorted(set(weight_map.values())):
                state.update(load_file(os.path.join(path, shard)))
        else:
            for f in sorted(os.listdir(path)):
                if f.endswith(".safetensors"):
                    state.update(load_file(os.path.join(path, f)))
    else:
        state = load_file(path)
    return state


def _strip_prefix(state: dict, prefixes: tuple[str, ...]) -> dict:
    out = {}
    for key, value in state.items():
        for prefix in prefixes:
            if key.startswith(prefix):
                key = key[len(prefix):]
                break
        out[key] = value
    return out


def _set(tree: dict, path: str, value: np.ndarray):
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


# --------------------------------------------------------------------- #
# BERT
# --------------------------------------------------------------------- #

_BERT_FIXED = {
    "embeddings.word_embeddings.weight": ("encoder/embeddings/word_embeddings/embedding", False),
    "embeddings.position_embeddings.weight": ("encoder/embeddings/position_embeddings/embedding", False),
    "embeddings.token_type_embeddings.weight": ("encoder/embeddings/token_type_embeddings/embedding", False),
    "embeddings.LayerNorm.weight": ("encoder/embeddings/norm/scale", False),
    "embeddings.LayerNorm.bias": ("encoder/embeddings/norm/bias", False),
    "pooler.dense.weight": ("pooler/kernel", True),
    "pooler.dense.bias": ("pooler/bias", False),
    "classifier.weight": ("classifier/kernel", True),
    "classifier.bias": ("classifier/bias", False),
}

_BERT_LAYER = {
    "attention.self.query.weight": ("attention/query/kernel", True),
    "attention.self.query.bias": ("attention/query/bias", False),
    "attention.self.key.weight": ("attention/key/kernel", True),
    "attention.self.key.bias": ("attention/key/bias", False),
    "attention.self.value.weight": ("attention/value/kernel", True),
    "attention.self.value.bias": ("attention/value/bias", False),
    "attention.output.dense.weight": ("attention/out/kernel", True),
    "attention.output.dense.bias": ("attention/out/bias", False),
    "attention.output.LayerNorm.weight": ("attention_norm/scale", False),
    "attention.output.LayerNorm.bias": ("attention_norm/bias", False),
    "intermediate.dense.weight": ("ffn/intermediate/kernel", True),
    "intermediate.dense.bias": ("ffn/intermediate/bias", False),
    "output.dense.weight": ("ffn/output/kernel", True),
    "output.dense.bias": ("ffn/output/bias", False),
    "output.LayerNorm.weight": ("ffn_norm/scale", False),
    "output.LayerNorm.bias": ("ffn_norm/bias", False),
}


def convert_hf_bert_state(state: dict[str, np.ndarray]) -> dict:
    """HF ``bert-*`` (BertForSequenceClassification) -> our param pytree."""
    state = _strip_prefix(state, ("bert.",))
    tree: dict = {}
    for hf_key, (ours, transpose) in _BERT_FIXED.items():
        if hf_key in state:
            value = state[hf_key]
            _set(tree, ours, value.T if transpose else value)
    layer_re = re.compile(r"encoder\.layer\.(\d+)\.(.+)")
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        if rest in _BERT_LAYER:
            ours, transpose = _BERT_LAYER[rest]
            _set(tree, f"encoder/layer_{idx}/{ours}", value.T if transpose else value)
    return tree


def load_hf_bert(checkpoint_path: str, config=None):
    """Build a BERT Model and load HF weights into it."""
    import jax

    from .bert import BertConfig, create_bert_model

    state = read_safetensors_state(checkpoint_path)
    tree = convert_hf_bert_state(state)
    model = create_bert_model(config or BertConfig.base())
    _merge_into(model, tree)
    return model


# --------------------------------------------------------------------- #
# Llama
# --------------------------------------------------------------------- #

_LLAMA_FIXED = {
    "model.embed_tokens.weight": ("embed_tokens/embedding", False),
    "model.norm.weight": ("final_norm/scale", False),
    "lm_head.weight": ("lm_head/kernel", True),
}

_LLAMA_LAYER = {
    "self_attn.q_proj.weight": ("attn/q_proj/kernel", True),
    "self_attn.k_proj.weight": ("attn/k_proj/kernel", True),
    "self_attn.v_proj.weight": ("attn/v_proj/kernel", True),
    "self_attn.o_proj.weight": ("attn/o_proj/kernel", True),
    "mlp.gate_proj.weight": ("mlp/gate_proj/kernel", True),
    "mlp.up_proj.weight": ("mlp/up_proj/kernel", True),
    "mlp.down_proj.weight": ("mlp/down_proj/kernel", True),
    "input_layernorm.weight": ("input_norm/scale", False),
    "post_attention_layernorm.weight": ("post_attn_norm/scale", False),
}


def convert_hf_llama_state(state: dict[str, np.ndarray], scan_layers: bool = True) -> dict:
    """HF ``*ForCausalLM`` Llama -> our param pytree. With ``scan_layers``
    the per-layer weights are stacked along a leading layer dim to match
    the scanned module layout (``layers/block/...``)."""
    tree: dict = {}
    for hf_key, (ours, transpose) in _LLAMA_FIXED.items():
        if hf_key in state:
            value = state[hf_key]
            _set(tree, ours, value.T if transpose else value)
    # lm_head may be tied to embeddings in some checkpoints
    if "lm_head" not in tree and "model.embed_tokens.weight" in state:
        _set(tree, "lm_head/kernel", state["model.embed_tokens.weight"].T)

    layer_re = re.compile(r"model\.layers\.(\d+)\.(.+)")
    per_layer: dict[int, dict[str, np.ndarray]] = {}
    for key, value in state.items():
        m = layer_re.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        if rest in _LLAMA_LAYER:
            ours, transpose = _LLAMA_LAYER[rest]
            per_layer.setdefault(idx, {})[ours] = value.T if transpose else value
    if not per_layer:
        return tree
    n_layers = max(per_layer) + 1
    if scan_layers:
        for ours in _LLAMA_LAYER.values():
            name = ours[0]
            stacked = np.stack([per_layer[i][name] for i in range(n_layers)])
            _set(tree, f"layers/block/{name}", stacked)
    else:
        for i in range(n_layers):
            for name, value in per_layer[i].items():
                _set(tree, f"layer_{i}/{name}", value)
    return tree


def load_hf_llama(checkpoint_path: str, config=None):
    import jax

    from .llama import LlamaConfig, create_llama_model

    state = read_safetensors_state(checkpoint_path)
    config = config or LlamaConfig.llama2_7b()
    tree = convert_hf_llama_state(state, scan_layers=config.scan_layers)
    model = create_llama_model(config)
    _merge_into(model, tree)
    return model


def _merge_into(model, tree: dict):
    """Replace model params with imported values (shape-checked; values not
    present keep their initialisation)."""
    import jax

    from ..parallel.sharding import path_str

    flat_imported = {}

    def flatten(node, prefix=""):
        if isinstance(node, dict):
            for k, v in node.items():
                flatten(v, f"{prefix}{k}/")
        else:
            flat_imported[prefix[:-1]] = node

    flatten(tree)

    leaves, treedef = jax.tree_util.tree_flatten_with_path(model.params)
    new_leaves = []
    imported = 0
    for kp, old in leaves:
        key = path_str(kp)
        if key in flat_imported:
            new = np.asarray(flat_imported[key])
            if tuple(new.shape) != tuple(old.shape):
                raise ValueError(f"shape mismatch importing {key}: {new.shape} vs {old.shape}")
            new_leaves.append(new.astype(old.dtype))
            imported += 1
        else:
            new_leaves.append(old)
    model.params = jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(model.params), new_leaves)
    model.imported_weight_count = imported
    return model
