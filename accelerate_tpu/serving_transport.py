"""Framed socket transport for the multi-process serving fleet.

The process supervisor (:mod:`accelerate_tpu.serving_proc`) talks to its
engine workers over one localhost TCP connection per worker. Every
message is ONE length-prefixed frame: a fixed 16-byte header, a compact
JSON control part, and an optional raw binary part — the binary part is
exactly the PR-15 :class:`~accelerate_tpu.serving_fleet.HandoffCodec`
npz blob (prefill handoffs) or the failover-snapshot bundle encoded by
:func:`encode_snapshots` (same raw-leaf-bytes + shape technique; the
receiving engine's row template stays the single source of truth for
dtypes and tree structure, and the v2 ``tmeta`` trace id rides each
snapshot across the process boundary).

Failure is structured, never a hang: a short read at EOF (worker died
mid-frame) raises :class:`PeerClosedError` with the byte position, a bad
magic / version / crc32 or an oversized declared length raises
:class:`FrameError` BEFORE any allocation for the body, and socket
timeouts propagate as ``socket.timeout`` for the supervisor's
degraded/quarantined escalation. ``recv_exact`` loops over partial
reads, so TCP segmentation (short writes on the peer) is invisible to
the protocol layer.

Concurrency contract (the TPU9xx gate lints this module): all functions
here are plain blocking socket calls — callers must never hold a lock
across them. The worker is single-threaded; the supervisor confines all
transport IO to its pump loop.
"""

from __future__ import annotations

import io
import json
import socket
import struct
import zlib

import numpy as np

#: frame header: magic, version, reserved flags, json bytes, blob bytes,
#: crc32(json + blob)
_HEADER = struct.Struct(">2sBBIII")
MAGIC = b"AT"
VERSION = 1

#: refuse frames larger than this before reading the body (a corrupt
#: length field must not allocate gigabytes or desync into a hang)
MAX_FRAME_BYTES = 256 << 20


class TransportError(RuntimeError):
    """Base class for structured transport failures."""


class FrameError(TransportError):
    """The byte stream is not a valid frame (bad magic/version, crc32
    mismatch, oversized declared length, or undecodable JSON). The
    connection is unrecoverable — close it and treat the peer as dead."""


class PeerClosedError(TransportError):
    """EOF before a complete frame — the peer process died (or closed)
    mid-message. Carries how far the read got."""

    def __init__(self, msg: str, got: int = 0, want: int = 0):
        super().__init__(msg)
        self.got = int(got)
        self.want = int(want)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes, looping over partial reads. EOF
    mid-read raises :class:`PeerClosedError` (worker death mid-frame);
    a socket timeout propagates unchanged."""
    if n == 0:
        return b""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise PeerClosedError(
                f"peer closed after {got}/{n} bytes of a frame", got=got, want=n
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_msg(sock: socket.socket, obj: dict, blob: bytes = b"", *,
             max_frame: int = MAX_FRAME_BYTES) -> int:
    """Send one frame (``obj`` as compact JSON + optional binary
    ``blob``). Returns the total bytes written. ``sendall`` under the
    hood, so short writes are already looped by the socket layer."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) + len(blob) > max_frame:
        raise FrameError(
            f"frame of {len(payload) + len(blob)} bytes exceeds the "
            f"{max_frame}-byte transport cap"
        )
    crc = zlib.crc32(blob, zlib.crc32(payload))
    header = _HEADER.pack(MAGIC, VERSION, 0, len(payload), len(blob), crc)
    sock.sendall(header + payload + blob)
    return len(header) + len(payload) + len(blob)


def recv_msg(sock: socket.socket, *, max_frame: int = MAX_FRAME_BYTES):
    """Receive one frame; ``(obj, blob)``. Raises :class:`FrameError`
    on a corrupt/oversized frame, :class:`PeerClosedError` on EOF
    mid-frame, and lets ``socket.timeout`` propagate."""
    header = recv_exact(sock, _HEADER.size)
    magic, version, _flags, jlen, blen, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r} (expected {MAGIC!r})")
    if version != VERSION:
        raise FrameError(f"unsupported transport version {version} (speak {VERSION})")
    if jlen + blen > max_frame:
        raise FrameError(
            f"declared frame of {jlen + blen} bytes exceeds the "
            f"{max_frame}-byte transport cap"
        )
    payload = recv_exact(sock, jlen)
    blob = recv_exact(sock, blen)
    if zlib.crc32(blob, zlib.crc32(payload)) != crc:
        raise FrameError("frame crc32 mismatch — payload corrupt in transit")
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"frame JSON undecodable: {e}") from None
    return obj, blob


def request(sock: socket.socket, obj: dict, blob: bytes = b"", *,
            timeout=None, max_frame: int = MAX_FRAME_BYTES):
    """One strict request/response round trip (the supervisor side).
    ``timeout`` covers both legs; a reply carrying ``{"err": ...}``
    raises :class:`WorkerError` with the worker's structured detail."""
    sock.settimeout(timeout)
    send_msg(sock, obj, blob, max_frame=max_frame)
    reply, rblob = recv_msg(sock, max_frame=max_frame)
    if isinstance(reply, dict) and reply.get("err") is not None:
        raise WorkerError(reply["err"])
    return reply, rblob


class WorkerError(TransportError):
    """The worker replied with a structured error (``{"err": {...}}``):
    the request failed application-side (bad uid, import rejected, a
    poison trip) but the worker and the connection are still alive."""

    def __init__(self, err):
        detail = err if isinstance(err, dict) else {"detail": str(err)}
        super().__init__(detail.get("detail") or str(detail))
        self.kind = detail.get("kind", "error")
        self.detail = detail


# --------------------------------------------------------------------- #
# failover-snapshot bundle codec
# --------------------------------------------------------------------- #
# ``ServingEngine.export_inflight`` snapshots cross the process boundary
# in one npz bundle: per-snapshot namespaced arrays, KV leaves as raw
# uint8 + shape exactly like HandoffCodec (dtype-agnostic; the importing
# engine's ``_row_template`` restores dtype and tree structure). The
# JSON half of the frame carries ``snapshot_meta`` so the jax-free
# supervisor can route, price, and account each snapshot without ever
# decoding the blob.


def snapshot_meta(snaps: list) -> list:
    """Supervisor-visible metadata for each snapshot: identity, progress,
    and the KV payload size actually serialized (``kv_bytes`` is the
    byte-for-byte accounting the priced failover pins against the
    ``rows * bytes_per_token + fixed`` prediction)."""
    import jax

    meta = []
    for s in snaps:
        kv_bytes = 0
        if s.get("cache") is not None:
            kv_bytes = sum(
                np.asarray(leaf).nbytes for leaf in jax.tree_util.tree_leaves(s["cache"])
            )
        meta.append(
            {
                "uid": int(s["uid"]),
                "prompt_len": int(np.asarray(s["prompt"]).size),
                "generated": len(s.get("out_tokens") or []),
                "max_new_tokens": int(s["max_new_tokens"]),
                "priority": int(s.get("priority", 0)),
                "rows": int(s.get("rows") or 0),
                "has_kv": s.get("cache") is not None,
                "kv_bytes": int(kv_bytes),
                "trace": s.get("trace"),
            }
        )
    return meta


def encode_snapshots(snaps: list) -> tuple:
    """``(meta, blob)`` for a list of ``export_inflight`` snapshots.
    Worker-side only (touches jax for the KV leaves)."""
    import jax

    arrays = {}
    for i, s in enumerate(snaps):
        p = f"s{i}_"
        arrays[p + "prompt"] = np.asarray(s["prompt"], np.int32)
        arrays[p + "key_data"] = np.asarray(s["key_data"])
        arrays[p + "out"] = np.asarray(s.get("out_tokens") or [], np.int64)
        arrays[p + "lps"] = np.asarray(s.get("out_lps") or [], np.float64)
        stops = s.get("stop_sequences") or ()
        arrays[p + "stop_flat"] = np.asarray(
            [t for seq in stops for t in seq], np.int64
        )
        arrays[p + "stop_lens"] = np.asarray([len(seq) for seq in stops], np.int64)
        leaves = jax.tree_util.tree_leaves(s["cache"]) if s.get("cache") is not None else []
        arrays[p + "imeta"] = np.asarray(
            [
                int(s["uid"]),
                int(s["max_new_tokens"]),
                int(s.get("priority", 0)),
                int(s.get("rows") or 0),
                len(leaves),
                -1 if s.get("trace") is None else int(s["trace"]),
            ],
            np.int64,
        )
        for j, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            arrays[p + f"leaf_{j}"] = np.frombuffer(arr.tobytes(), np.uint8)
            arrays[p + f"shape_{j}"] = np.asarray(arr.shape, np.int64)
    arrays["count"] = np.asarray([len(snaps)], np.int64)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return snapshot_meta(snaps), buf.getvalue()


def decode_snapshots(blob: bytes, engine) -> list:
    """Rebuild the snapshot dicts against ``engine``'s row template;
    each result feeds ``engine.import_inflight`` unchanged."""
    import jax

    template = jax.tree_util.tree_leaves(engine._row_template)
    treedef = jax.tree_util.tree_structure(engine._row_template)
    snaps = []
    with np.load(io.BytesIO(blob)) as z:
        count = int(z["count"][0])
        for i in range(count):
            p = f"s{i}_"
            imeta = z[p + "imeta"]
            uid, max_new, priority, rows, n_leaves, trace = (int(v) for v in imeta)
            stops, flat = [], [int(t) for t in z[p + "stop_flat"]]
            for ln in z[p + "stop_lens"]:
                stops.append(tuple(flat[: int(ln)]))
                flat = flat[int(ln):]
            snap = {
                "uid": uid,
                "prompt": np.asarray(z[p + "prompt"], np.int32),
                "max_new_tokens": max_new,
                "out_tokens": [int(t) for t in z[p + "out"]],
                "out_lps": [float(v) for v in z[p + "lps"]],
                "stop_sequences": tuple(stops),
                "priority": priority,
                "trace": None if trace < 0 else trace,
                "key_data": np.asarray(z[p + "key_data"]),
            }
            if n_leaves:
                if n_leaves != len(template):
                    raise ValueError(
                        f"snapshot has {n_leaves} KV leaves; this engine's row "
                        f"template has {len(template)} — engines disagree on the "
                        "cache pytree"
                    )
                leaves = []
                for j, t in enumerate(template):
                    raw = z[p + f"leaf_{j}"].tobytes()
                    shape = tuple(int(d) for d in z[p + f"shape_{j}"])
                    leaves.append(np.frombuffer(raw, t.dtype).reshape(shape))
                snap["cache"] = jax.tree_util.tree_unflatten(treedef, leaves)
                snap["rows"] = rows
            snaps.append(snap)
    return snaps
