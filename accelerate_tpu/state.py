"""Process/topology singletons: ``PartialState``, ``AcceleratorState``,
``GradientState``.

Reference analogue: src/accelerate/state.py (1347 LoC). The reference's
``PartialState`` must probe seven native backends and run a rendezvous
(state.py:746-812, init_process_group at :236); here the entire bootstrap is
``jax.distributed.initialize`` (DCN rendezvous) + mesh construction — ICI
collectives need no process groups at all, XLA inserts them from shardings.

The shared-dict (borg) pattern is kept (reference: state.py:163,179): every
``PartialState()`` constructed anywhere in the process sees the same state,
and ``Accelerator()`` can be constructed many times cheaply.
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager
from typing import Any, Callable, Optional


from .utils.dataclasses import DistributedType, MixedPrecisionPolicy, ParallelismPlugin, PrecisionType
from .utils.environment import parse_flag_from_env

logger = logging.getLogger(__name__)


def _jax():
    import jax

    return jax


class PartialState:
    """Topology singleton (reference: state.py:124).

    Handles multi-host rendezvous (``jax.distributed.initialize``), exposes
    rank/world/device info, and the process-control helpers
    (``wait_for_everyone``, ``main_process_first``, ``split_between_processes``,
    ``on_main_process`` — reference: state.py:417-560).
    """

    _shared_state: dict[str, Any] = {}
    _know_initialized = False

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        jax = _jax()

        # Multi-host rendezvous over DCN (reference boundary analogue:
        # torch.distributed.init_process_group, state.py:236).
        # NB: no jax.devices()/process_count() calls may happen before
        # jax.distributed.initialize() — backend init is one-shot, so the
        # guard is an env flag, not a backend query.
        coordinator = kwargs.pop("coordinator_address", None) or os.environ.get("ACCELERATE_COORDINATOR_ADDRESS")
        num_processes_env = kwargs.pop("num_processes", None) or os.environ.get("ACCELERATE_NUM_PROCESSES")
        process_id = kwargs.pop("process_id", None) or os.environ.get("ACCELERATE_PROCESS_ID")
        if coordinator is not None and not parse_flag_from_env("ACCELERATE_DISTRIBUTED_INITIALIZED"):
            local_ids = kwargs.pop("local_device_ids", None)
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=int(num_processes_env) if num_processes_env is not None else None,
                process_id=int(process_id) if process_id is not None else None,
                local_device_ids=local_ids,
            )
            os.environ["ACCELERATE_DISTRIBUTED_INITIALIZED"] = "1"

        if cpu or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # force the CPU backend (test/debug path; also how the fake
            # 8-device mesh CI mode runs). The env var alone is NOT enough:
            # the axon TPU plugin can win over JAX_PLATFORMS and then wedge
            # on an unreachable tunnel — the jax.config override is
            # authoritative, so honor the env request here too.
            jax.config.update("jax_platforms", "cpu")

        self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        self._cpu = cpu
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED")
        self.backend = jax.default_backend()
        self._devices = jax.devices()
        self._local_devices = jax.local_devices()
        self.num_processes_host = jax.process_count()
        self.process_index_host = jax.process_index()
        self.initialized = True

    # -- identity ----------------------------------------------------------

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_initialized", False)

    @initialized.setter
    def initialized(self, value: bool):
        self._shared_state["_initialized"] = value

    @property
    def device(self):
        """The first local device (reference ``self.device``, state.py:814)."""
        return self._local_devices[0]

    @property
    def devices(self):
        return self._devices

    @property
    def local_devices(self):
        return self._local_devices

    @property
    def num_devices(self) -> int:
        return len(self._devices)

    @property
    def local_device_count(self) -> int:
        return len(self._local_devices)

    @property
    def num_processes(self) -> int:
        """Number of *host processes*. NB: the reference's "process" is one
        per accelerator; on TPU one process drives several chips, so
        data-parallel sharding happens per-device, not per-process."""
        return self.num_processes_host

    @property
    def process_index(self) -> int:
        return self.process_index_host

    @property
    def local_process_index(self) -> int:
        # one process per host on TPU pods; the N-local-process testing
        # launcher sets the env so rank gating (print/tqdm/local-main
        # contexts) behaves like the reference's torchrun LOCAL_RANK
        return int(os.environ.get("ACCELERATE_LOCAL_PROCESS_ID", 0))

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    @property
    def distributed_type(self) -> DistributedType:
        state = AcceleratorState._shared_state
        if state.get("_initialized") and state.get("mesh") is not None:
            return DistributedType.from_mesh_sizes(dict(state["mesh"].shape))
        return DistributedType.DATA_PARALLEL if self.num_devices > 1 else DistributedType.NO

    @property
    def use_distributed(self) -> bool:
        return self.num_devices > 1 or self.num_processes > 1

    # -- process control ---------------------------------------------------

    def wait_for_everyone(self):
        """Cross-host barrier (reference: utils/other.py:302 incl.
        ``xm.rendezvous``). Single-process: no-op."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    @contextmanager
    def main_process_first(self):
        """Main process runs the body first, others wait (reference:
        state.py:508) — e.g. dataset download/caching."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def local_main_process_first(self):
        if not self.is_local_main_process:
            self.wait_for_everyone()
        yield
        if self.is_local_main_process:
            self.wait_for_everyone()

    @staticmethod
    def _pad_tail(chunk, target: int, full):
        """Grow ``chunk`` to ``target`` rows by repeating ``full``'s last row.
        Arrays stay arrays (the reference pads tensors with torch.cat,
        state.py:446-462); lists/tuples pad to a list."""
        if target <= len(chunk) or not len(full):
            return chunk
        if hasattr(chunk, "shape") and hasattr(chunk, "dtype"):  # np/jax array
            import numpy as _np

            reps = target - len(chunk)
            last = full[-1:]
            if isinstance(chunk, _np.ndarray):
                return _np.concatenate([chunk] + [_np.asarray(last)] * reps, axis=0)
            import jax.numpy as jnp

            return jnp.concatenate([chunk] + [jnp.asarray(last)] * reps, axis=0)
        out = list(chunk)
        while len(out) < target:
            out.append(full[-1])
        return out

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/tuple/dict/array evenly across processes (reference:
        state.py:417-506). Yields this process's slice; ``apply_padding``
        repeats the last element/row so every process gets equal length —
        tensor inputs are padded as tensors, matching the reference."""
        if self.num_processes == 1:
            yield inputs
            return
        if isinstance(inputs, dict):
            # split dict VALUES row-wise (len(dict) would count keys);
            # reference requires equal-length values (state.py:468-474)
            lengths = {k: len(v) for k, v in inputs.items()}
            if len(set(lengths.values())) > 1:
                raise ValueError(f"cannot split dict with unequal value lengths: {lengths}")
            length = next(iter(lengths.values())) if lengths else 0
        else:
            length = len(inputs)
        num_per = length // self.num_processes
        remainder = length % self.num_processes
        start = self.process_index * num_per + min(self.process_index, remainder)
        end = start + num_per + (1 if self.process_index < remainder else 0)
        if isinstance(inputs, dict):
            chunk = {k: v[start:end] for k, v in inputs.items()}
        else:
            chunk = inputs[start:end]
        if apply_padding and length:
            target = num_per + (1 if remainder else 0)
            if isinstance(chunk, dict):
                chunk = {k: self._pad_tail(v, target, inputs[k]) for k, v in chunk.items()}
            else:
                chunk = self._pad_tail(chunk, target, inputs)
        yield chunk

    def on_main_process(self, function: Callable) -> Callable:
        """Decorator: run only on the main process (reference: state.py:549)."""

        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_local_main_process(self, function: Callable) -> Callable:
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable = None, process_index: int = None) -> Callable:
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return wrapper

    def on_last_process(self, function: Callable) -> Callable:
        def wrapper(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return wrapper

    def print(self, *args, **kwargs):
        if self.is_local_main_process:
            print(*args, **kwargs)

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Backend: {self.backend}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Num devices: {self.num_devices}\n"
            f"Device: {self.device}\n"
        )

    def destroy_process_group(self):
        """Shut down the distributed runtime (tests / clean exit)."""
        jax = _jax()
        if self.num_processes > 1:
            try:
                jax.distributed.shutdown()
            except Exception:  # pragma: no cover
                pass

    @classmethod
    def _reset_state(cls):
        """Reset the singleton (test harness; reference: state.py
        ``_reset_state`` used by AccelerateTestCase, testing.py:639)."""
        cls._shared_state.clear()


class AcceleratorState:
    """Adds precision policy + mesh to :class:`PartialState`
    (reference: state.py:863)."""

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: Optional[str] = None,
        cpu: bool = False,
        parallelism_plugin: Optional[ParallelismPlugin] = None,
        _from_accelerator: bool = False,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if mixed_precision is not None and mixed_precision != self.mixed_precision:
                logger.warning(
                    "AcceleratorState already initialized with mixed_precision=%s; ignoring %s",
                    self.mixed_precision,
                    mixed_precision,
                )
            return
        self.partial_state = PartialState(cpu=cpu, **kwargs)
        mixed_precision = (
            mixed_precision
            if mixed_precision is not None
            else os.environ.get("ACCELERATE_MIXED_PRECISION", "no")
        )
        self.mixed_precision = str(PrecisionType(mixed_precision))
        self.dtype_policy = MixedPrecisionPolicy.from_mixed_precision(self.mixed_precision)
        self.parallelism_plugin = parallelism_plugin or ParallelismPlugin.from_env()
        self.mesh = self.parallelism_plugin.mesh_config.build(self.partial_state.devices)
        self.initialized = True

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_initialized", False)

    @initialized.setter
    def initialized(self, value: bool):
        self._shared_state["_initialized"] = value

    @property
    def distributed_type(self) -> DistributedType:
        return DistributedType.from_mesh_sizes(dict(self.mesh.shape))

    def __getattr__(self, name: str):
        # delegate topology attrs to PartialState (reference does the same
        # via __getattr__, state.py)
        if name.startswith("_") or "partial_state" not in self.__dict__:
            raise AttributeError(name)
        return getattr(self.partial_state, name)

    def __repr__(self) -> str:
        return repr(self.partial_state) + f"Mixed precision: {self.mixed_precision}\nMesh: {dict(self.mesh.shape)}\n"

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False):
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()


class GradientState:
    """Gradient-accumulation bookkeeping singleton (reference: state.py:1207).

    Tracks the accumulation counter, the ``sync_gradients`` flag, active
    dataloaders and the uneven-tail ``remainder`` that drives
    ``gather_for_metrics`` truncation (reference: state.py:1300-1340,
    data_loader.py:365-405)."""

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_plugin=None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.current_step = 0
            self.plugin_kwargs = {}
            self.initialized = True
        if gradient_accumulation_plugin is not None:
            self.plugin_kwargs = gradient_accumulation_plugin.to_dict()

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_initialized", False)

    @initialized.setter
    def initialized(self, value: bool):
        self._shared_state["_initialized"] = value

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", True)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        """Number of padding samples in the final uneven batch (negative
        convention matches the reference: -1 = unknown)."""
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync_gradients: bool):
        self.sync_gradients = sync_gradients

    def _add_dataloader(self, dataloader):
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader):
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    def __repr__(self) -> str:
        return (
            f"Sync gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
        )

    @classmethod
    def _reset_state(cls):
        cls._shared_state.clear()
