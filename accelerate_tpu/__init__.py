"""accelerate_tpu — TPU-native training orchestration.

The capabilities of HF Accelerate (reference: sbhavani/accelerate @
1.10.0.dev0), re-designed for the TPU execution model: one
``jax.sharding.Mesh``, declarative ``NamedSharding`` layouts, and a single
jitted train step. Every reference "strategy" (DDP/FSDP/ZeRO/TP/SP) is a
mesh layout policy here, not a separate code path.
"""

__version__ = "0.1.0"

from .state import AcceleratorState, GradientState, PartialState
from .logging import get_logger
from .utils import (
    CompileKwargs,
    DataLoaderConfiguration,
    DistributedType,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    ParallelismPlugin,
    PrecisionType,
    ProjectConfiguration,
    find_executable_batch_size,
    set_seed,
)
from .parallel import MeshConfig

# Heavier modules (accelerator, data_loader, checkpointing, tracking, models)
# are imported lazily to keep `import accelerate_tpu` light; the canonical
# user entrypoint is re-exported here once defined.
from .accelerator import Accelerator  # noqa: E402
from .modeling import Model  # noqa: E402
from .data_loader import prepare_data_loader, skip_first_batches  # noqa: E402
from .optimizer import AcceleratedOptimizer  # noqa: E402
from .scheduler import AcceleratedScheduler  # noqa: E402
from .local_sgd import LocalSGD  # noqa: E402
from .generation import beam_search, generate, generate_seq2seq, per_token_latency  # noqa: E402
from .scheduling import (  # noqa: E402
    FleetRoutingPolicy,
    RoutingConfig,
    Scheduler,
    SchedulerConfig,
    ShedError,
)
from .serving import ServingEngine  # noqa: E402
from .serving_fleet import FleetConfig, FleetRouter, RadixPrefixCache  # noqa: E402
from .speculative import speculative_generate  # noqa: E402
from .launchers import debug_launcher, notebook_launcher  # noqa: E402
