# Repo quality/test targets (reference analogue: the reference Makefile's
# quality/style/test tiers).

.PHONY: quality style test test-slow test-all test-cli check-imports bench dryrun api-docs

# lint if ruff is installed (its exit code propagates); the zero-dep
# AST/import gates always run
quality:
	@if command -v ruff >/dev/null 2>&1; then ruff check accelerate_tpu tests examples; else echo "ruff not installed; skipping lint"; fi
	python scripts/check_repo.py

style:
	@if command -v ruff >/dev/null 2>&1; then ruff check --fix accelerate_tpu tests examples && ruff format accelerate_tpu tests examples; else echo "ruff not installed; style target is a no-op here"; fi

test:  # fast tier (addopts excludes -m slow)
	python -m pytest tests/ -q

test-slow:  # subprocess/integration tier
	python -m pytest tests/ -q -m slow --override-ini addopts=""

test-all:
	python -m pytest tests/ -q -m "" --override-ini addopts=""

test-cli:
	python -m pytest tests/test_cli.py -q

api-docs:
	python scripts/gen_api_docs.py

bench:
	python bench.py

dryrun:
	python __graft_entry__.py 8
