# Repo quality/test targets (reference analogue: the reference Makefile's
# quality/style/test tiers).

.PHONY: quality style test test-fast test-cli check-imports bench dryrun

# lint if ruff is installed; the zero-dep AST/import gates always run
quality:
	@command -v ruff >/dev/null 2>&1 && ruff check accelerate_tpu tests examples || true
	python scripts/check_repo.py

style:
	@command -v ruff >/dev/null 2>&1 && ruff check --fix accelerate_tpu tests examples && ruff format accelerate_tpu tests examples || echo "ruff not installed; style target is a no-op here"

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -m "not slow"

test-cli:
	python -m pytest tests/test_cli.py -q

bench:
	python bench.py

dryrun:
	python __graft_entry__.py 8
