# Repo quality/test targets (reference analogue: the reference Makefile's
# quality/style/test tiers).

.PHONY: quality style lint lint-sarif divergence flight-check perf-check numerics-check pipe-check fleet-check kernel-check tune-selfcheck tune-bench pipeline-bench telemetry-selfcheck trace-selfcheck trace-bench ft-selfcheck aot-selfcheck test test-slow test-all test-cli check-imports bench dryrun api-docs cache-pack cache-seed

# Persistent XLA compile cache (tests/conftest.py points every run and its
# subprocess children here). cache-pack snapshots a warm cache into a
# shareable artifact; cache-seed restores it into an EMPTY dir only — a
# half-written or corrupt cache segfaults XLA:CPU mid-suite, so a non-empty
# dir is left alone (wipe with `rm -rf $(JAX_CACHE_DIR)` if a run dies with
# a faulthandler dump, then re-seed). CI: store the artifact, `make
# cache-seed test`. See docs/usage_guides/testing.md for measured times.
JAX_CACHE_DIR ?= /tmp/accelerate_tpu_jax_cache
JAX_CACHE_ARTIFACT ?= .cache/jax_compile_cache.tar.gz

cache-pack:
	@mkdir -p $(dir $(JAX_CACHE_ARTIFACT))
	@tar -C $(JAX_CACHE_DIR) -czf $(JAX_CACHE_ARTIFACT) .
	@du -h $(JAX_CACHE_ARTIFACT)

cache-seed:
	@if [ -f $(JAX_CACHE_ARTIFACT) ] && [ -z "$$(ls -A $(JAX_CACHE_DIR) 2>/dev/null)" ]; then \
		mkdir -p $(JAX_CACHE_DIR) && tar -C $(JAX_CACHE_DIR) -xzf $(JAX_CACHE_ARTIFACT) && \
		echo "seeded $(JAX_CACHE_DIR) from $(JAX_CACHE_ARTIFACT)"; \
	else echo "cache-seed: nothing to do (no artifact, or cache already warm)"; fi

# lint if ruff is installed (its exit code propagates); the zero-dep
# AST/import gates always run
quality: lint
	@if command -v ruff >/dev/null 2>&1; then ruff check accelerate_tpu tests examples; else echo "ruff not installed; skipping lint"; fi
	python scripts/check_repo.py

# TPU correctness linter: self-lint the tree (exit nonzero on any
# error-severity finding) + prove every rule fires on its seeded-defect
# fixture. Runs on the CPU backend — safe on machines with no TPU.
# The flight-check gate rides along non-strict: TPU3xx warnings print but
# don't fail the build (yet).
lint:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli lint accelerate_tpu --selfcheck
	$(MAKE) --no-print-directory divergence
	$(MAKE) --no-print-directory perf-check
	$(MAKE) --no-print-directory numerics-check
	$(MAKE) --no-print-directory tune-selfcheck
	$(MAKE) --no-print-directory pipe-check
	$(MAKE) --no-print-directory fleet-check
	$(MAKE) --no-print-directory kernel-check
	-$(MAKE) --no-print-directory flight-check
	-$(MAKE) --no-print-directory telemetry-selfcheck
	-$(MAKE) --no-print-directory trace-selfcheck
	-$(MAKE) --no-print-directory ft-selfcheck
	-$(MAKE) --no-print-directory aot-selfcheck

# Multi-host divergence analyzer (TPU4xx): prove TPU401-405 fire on their
# seeded deadlock fixtures (and the clean fixture stays quiet), then
# self-analyze the tree. This gate is STRICT for the TPU401-403 errors —
# a collective not every rank reaches is a guaranteed all-host hang —
# while the TPU404/405 warnings report but pass. Pure AST, no jax needed.
divergence:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli divergence accelerate_tpu --selfcheck

# Merged SARIF 2.1.0 artifact for GitHub code scanning: the AST,
# divergence, numerics, pipe, fleet, and kernel tiers each contribute one
# runs[] entry (six runs; scripts/merge_sarif.py's test pins the count).
# Findings don't fail this target (make lint is the gate); the artifact
# is for PR annotation.
lint-sarif:
	@mkdir -p .cache
	-env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli lint accelerate_tpu --format sarif > .cache/lint.sarif
	-env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli divergence accelerate_tpu --format sarif > .cache/divergence.sarif
	-env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli numerics-check accelerate_tpu --format sarif > .cache/numerics.sarif
	-env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli pipe-check \
		examples/by_feature/pipe_check.py::train_step --mesh pipe=4,data=2 --format sarif > .cache/pipe.sarif
	-env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli fleet-check \
		accelerate_tpu/serving_fleet.py accelerate_tpu/scheduling.py accelerate_tpu/ft \
		accelerate_tpu/telemetry/httpd.py accelerate_tpu/telemetry/flightrec.py \
		accelerate_tpu/telemetry/trace.py accelerate_tpu/serving_proc.py \
		accelerate_tpu/serving_transport.py --format sarif > .cache/fleet.sarif
	-env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli kernel-check \
		examples/by_feature/kernel_check.py::decode_step --format sarif > .cache/kernel.sarif
	python scripts/merge_sarif.py .cache/lint.sarif .cache/divergence.sarif .cache/numerics.sarif .cache/pipe.sarif .cache/fleet.sarif .cache/kernel.sarif -o lint-merged.sarif

# Static perf tier: prove TPU501-505 fire on their seeded defects, each
# clean twin stays silent, and the roofline math matches the hand-computed
# reference exactly — then roofline the example step over a fake 8-device
# CPU mesh. The dogfood pass is non-strict for warnings (TPU501/503-505
# print but pass) while TPU502 (redundant collective) is error-severity
# and gates strictly: re-reducing an already-uniform value has no
# legitimate use.
perf-check:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli perf-check --selfcheck \
		examples/by_feature/flight_check.py::train_step --mesh data=8

# Numerics tier: prove TPU601-606 fire on their seeded defects, each
# clean twin stays silent, and the interval arithmetic matches the
# hand-computed reference exactly — then interpret the example's
# mixed-precision step over a fake 8-device CPU mesh AND run the AST
# key-reuse tier over the whole tree. The gate is STRICT for TPU602
# (provable fp16/fp8 overflow has no legitimate use) via its error
# severity; TPU601/603-606 warnings report but pass.
numerics-check:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli numerics-check --selfcheck \
		examples/by_feature/numerics_check.py::train_step --mesh data=8
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli numerics-check accelerate_tpu
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli numerics-check examples

# Config tier (autotuner): prove TPU701-705 fire on their seeded
# misconfigurations (TPU701 end to end through a real single-candidate
# tune whose static peak HBM cannot fit a tiny budget) and every clean
# twin stays silent — then dogfood a real tune over the example train
# workload. The gate is STRICT for TPU701 (an infeasible declared config
# cannot run) via its error severity; TPU702-705 warnings report but
# pass.
tune-selfcheck:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli tune --selfcheck \
		examples/by_feature/tune.py::train_workload --mesh data=8 \
		--meshes "data=8;data=4,tensor=2" --compressions none,int8 --generation cpu

# Autotuner oracle A/B on CPU (committed evidence: BENCH_TUNE.json):
# static ranking vs StepTelemetry-measured step time on the train
# (mesh x zero x compression) and serving (buckets x token budget)
# toy workloads, exact predicted-vs-HLO wire agreement, the TPU701
# prune exercised, zero post-warmup recompiles. Exits nonzero unless
# report.ok.
tune-bench:
	env JAX_PLATFORMS=cpu python benchmarks/bench_tune.py --smoke

# Pipeline tier (pipemodel): prove TPU801-805 fire on their seeded
# schedule defects, every clean twin stays silent, and the bubble /
# roofline arithmetic matches the hand-computed reference exactly — then
# analyze the example's real pipeline_apply step on a fake 8-device CPU
# mesh (pipe=4 x data=2). The gate is STRICT for TPU804 (a collective
# over the pipe axis inside the tick body deadlocks or serializes the
# MPMD schedule) via its error severity; TPU801-803/805 warnings report
# but pass.
pipe-check:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli pipe-check --selfcheck \
		examples/by_feature/pipe_check.py::train_step --mesh pipe=4,data=2

# Fleet tier (hostsim + fleet_rules): prove TPU901-905 fire on their
# seeded defects (ABBA deadlock, unlocked cross-thread attribute,
# sleep-under-lock, protocol-invariant breaks, unjoined worker) and
# every clean twin stays silent — then dogfood the host-concurrency lint
# over the real fleet surface AND model-check the replica health state
# machine extracted from serving_fleet.py against the PR-15 invariants
# (plus the process supervisor's worker lifecycle from serving_proc.py:
# respawn cap, restart-storm breaker, shed-on-zero-routable).
# The gate is STRICT for TPU901 (a reachable ABBA deadlock) and TPU904
# (a protocol invariant violation or an unpinned failure path) via their
# error severity; TPU902/903/905 warnings report but pass. Pure stdlib —
# the fastest gate in the chain.
fleet-check:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli fleet-check --selfcheck \
		accelerate_tpu/serving_fleet.py accelerate_tpu/scheduling.py accelerate_tpu/ft \
		accelerate_tpu/telemetry/httpd.py accelerate_tpu/telemetry/flightrec.py \
		accelerate_tpu/telemetry/trace.py accelerate_tpu/serving_proc.py \
		accelerate_tpu/serving_transport.py

# Kernel tier (kernelmodel + kernel_rules): prove TPU1001-1006 fire on
# their seeded defects (VMEM overflow, ragged tile, index-map gap, alias
# hazard, unregistered call, drifted contract), every clean twin (the
# shipped reference kernels) stays silent, and the kernel cost math
# matches the hand-computed reference exactly — then trace the example
# decode step AND run the AST registration gate over every tree path
# that issues a pallas_call (ops/ registration is the tracked follow-up;
# the gate scopes to kernels/ + examples until those contracts land).
# The gate is STRICT for TPU1001/1003/1005 (an unlowerable block, a
# garbage output region, an invisible kernel cost) via their error
# severity; TPU1002/1004/1006 warnings report but pass.
kernel-check:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli kernel-check --selfcheck \
		examples/by_feature/kernel_check.py::decode_step --mesh data=8
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli kernel-check \
		accelerate_tpu/kernels examples

# Pipeline analyzer A/B on CPU (committed evidence: BENCH_PIPE.json):
# pipemodel's bubble-adjusted prediction vs StepTelemetry-measured step
# time across num_microbatches x stage counts on a real pipeline_apply
# workload: the predicted-best schedule must be the measured-best, with
# zero post-warmup recompiles. Exits nonzero unless report.ok.
pipeline-bench:
	env JAX_PLATFORMS=cpu python benchmarks/bench_pipeline.py --smoke

# SPMD flight-check: prove TPU301/302/303 fire on their seeded defects,
# then report the example step (peak HBM + collective traffic) on a fake
# 8-device CPU mesh.
flight-check:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli flight-check --selfcheck \
		examples/by_feature/flight_check.py::train_step --mesh data=8 --donate 0

# Runtime telemetry: 5-step CPU loop -> JSONL -> parse -> summarize; proves
# the event-log schema, the step split, the recompile watchdog, and the
# summarize CLI agree end to end.
telemetry-selfcheck:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli telemetry selfcheck

# Request tracing: seeded drift fixture (handoff moved fewer bytes than
# priced -> exactly ONE latched trace_drift) + clean twin (zero) through
# the full Tracer -> EventLog -> reconstruction -> chrome-export ->
# flight-recorder pipeline. Pure stdlib, no jax.
trace-selfcheck:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli trace selfcheck

# Tracing A/B on CPU (committed evidence: BENCH_TRACE.json): a traced
# disaggregated fleet under a control arm and a mid-decode crash arm;
# every request traced, frontier-contiguous segments reconcile with e2e
# latency, handoff/failover span bytes match the price models exactly,
# failover tokens+logprobs match the control arm, zero drift latched,
# and the dead replica's flight dump holds the injected fault. Exits
# nonzero unless report.ok.
trace-bench:
	env JAX_PLATFORMS=cpu python benchmarks/bench_serving.py --trace --smoke

# Fault tolerance: seeded good/uncommitted/corrupt/recoverable checkpoint
# fixtures -> prove manifest verify (crc32 + sizes), discovery walk-back,
# tmp GC/recovery, and protected pruning classify every one correctly;
# plus a mesh-mismatch (topology v2) fixture -> prove `checkpoints
# describe` classifies identical/elastic/unknown and prices the reshard.
ft-selfcheck:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli checkpoints verify --selfcheck

# Compile cache (aot/): cold compile -> serialized executable store ->
# second cache deserializes with ZERO XLA compiles -> a poisoned entry is
# rejected cleanly and healed. Proves the AOT warm-start loop on CPU.
aot-selfcheck:
	env JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli compile-cache --selfcheck

style:
	@if command -v ruff >/dev/null 2>&1; then ruff check --fix accelerate_tpu tests examples && ruff format accelerate_tpu tests examples; else echo "ruff not installed; style target is a no-op here"; fi

test: cache-seed  # fast tier (addopts excludes -m slow)
	python -m pytest tests/ -q

test-slow:  # subprocess/integration tier
	python -m pytest tests/ -q -m slow --override-ini addopts=""

test-all:
	python -m pytest tests/ -q -m "" --override-ini addopts=""

test-cli:
	python -m pytest tests/test_cli.py -q

api-docs:
	python scripts/gen_api_docs.py

bench:
	python bench.py

dryrun:
	python __graft_entry__.py 8
